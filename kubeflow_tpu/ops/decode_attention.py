"""Pallas flash-decode attention: one query token against a long KV cache.

Decode attention at long context is pure KV-bandwidth: every generated
token re-reads the whole (B, S, G, D) cache. The XLA einsum path
materializes (B, G, rep, 1, S) logits in HBM between two kernels and
re-reads them for the softmax/PV contraction; this kernel streams the
cache HBM→VMEM once per step in the canonical flash form instead —
grid (batch, kv_head, kv_blocks) with the kv axis innermost/sequential,
a running (max, sum, acc) recurrence in VMEM scratch, and position-masked
blocks past ``pos`` skipped entirely via pl.when (the cache is allocated
at max_seq_len but only ``pos+1`` entries are live).

GQA-native: the query arrives grouped (B, G, rep, D) and contracts
directly against the UN-repeated cache — the rep axis rides the sublanes
of one small matmul per block, so the cache is never materialized
rep× wide.

int8 KV composes: pass the per-position scales and the kernel dequantizes
in-register after the int8 block load — HBM sees half the bytes
(models/decode.py int8 KV cache).

On non-TPU backends the kernel runs in interpreter mode for tests.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128
DEFAULT_BLOCK_K = 1024


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, *rest, scale: float,
                   num_kv: int, block_k: int, quantized: bool):
    # operand list is conditional: scale refs exist only for int8 caches
    # (an unquantized call must not DMA dummy scale blocks every step)
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    kj = pl.program_id(2)
    pos = pos_ref[0, 0]

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # (rep, d)
        k = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
        v = v_ref[0, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (rep, bk)
        if quantized:
            # scales fold OUTSIDE the matmuls (per-kv-position, so they
            # distribute over the d contraction): logits pick up the K
            # scale; P picks up the V scale before the PV product. Keeps
            # the scale operand (1, bk)-shaped — lane-dim friendly.
            logits = logits * ks_ref[0, 0]                   # (1, bk)
        s_idx = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        valid = s_idx <= pos
        logits = jnp.where(valid, logits, _NEG_INF)
        m_prev = m_scr[:, :1]
        row_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, row_max)
        p = jnp.where(valid, jnp.exp(logits - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        if quantized:
            # V's per-position scale joins AFTER the softmax-denominator
            # sum (it belongs to V, not to the probabilities)
            p = p * vs_ref[0, 0]                             # (1, bk)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
            p.astype(jnp.float32), v, preferred_element_type=jnp.float32)

    # blocks entirely past the live cache frontier contribute nothing —
    # skipping them makes step cost track pos, not max_seq_len
    pl.when(kj * block_k <= pos)(compute)

    @pl.when(kj == num_kv - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _pick_block_k(S: int, want: int) -> int:
    """Largest divisor of S <= want, preferring 128-lane multiples. The
    auto path must never raise on a valid cache length — an odd
    max_seq_len just gets a less-ideal block."""
    if S <= want:
        return S
    for b in range(want, 127, -1):
        if S % b == 0 and b % 128 == 0:
            return b
    for b in range(want, 0, -1):
        if S % b == 0:
            return b
    return S


def flash_decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           pos: jax.Array, *,
                           k_scale: jax.Array | None = None,
                           v_scale: jax.Array | None = None,
                           block_k: int | None = None,
                           interpret: bool | None = None) -> jax.Array:
    """q: (B, G, rep, D) one grouped query token; k/v: (B, S, G, D) cache
    (int8 when ``k_scale``/``v_scale`` (B, S, G) are given, else compute
    dtype); pos: (B,) int32 — entries at s <= pos[b] are live. Returns
    (B, G, rep, D) in q's dtype. ``block_k=None`` picks the largest
    S-divisor <= DEFAULT_BLOCK_K; an explicit block must divide S."""
    B, G, rep, D = q.shape
    S = k.shape[1]
    quantized = k_scale is not None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_k is None:
        block_k = _pick_block_k(S, DEFAULT_BLOCK_K)
    block_k = min(block_k, S)
    if S % block_k:
        raise ValueError(f"cache length {S} not divisible by block_k "
                         f"{block_k}")
    num_kv = S // block_k
    scale = 1.0 / math.sqrt(D)
    kt = k.transpose(0, 2, 1, 3)                             # (B, G, S, D)
    vt = v.transpose(0, 2, 1, 3)
    pos2 = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(B, 1),
                            (B, 1))
    operands = [pos2, q, kt, vt]
    in_specs = [
        pl.BlockSpec((1, 1), lambda b, g, kj: (b, 0)),               # pos
        pl.BlockSpec((1, 1, rep, D), lambda b, g, kj: (b, g, 0, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, g, kj: (b, g, kj, 0)),                # k
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, g, kj: (b, g, kj, 0)),                # v
    ]
    if quantized:
        # (B, S, G) → (B, G, 1, S): the kernel folds these into the
        # (rep, bk) logits/probs, so the kv axis rides the 128-lane dim
        operands.append(
            k_scale.transpose(0, 2, 1)[:, :, None, :].astype(jnp.float32))
        operands.append(
            v_scale.transpose(0, 2, 1)[:, :, None, :].astype(jnp.float32))
        in_specs.extend([
            pl.BlockSpec((1, 1, 1, block_k),
                         lambda b, g, kj: (b, g, 0, kj)),            # ks
            pl.BlockSpec((1, 1, 1, block_k),
                         lambda b, g, kj: (b, g, 0, kj)),            # vs
        ])

    grid = (B, G, num_kv)
    kernel = functools.partial(_decode_kernel, scale=scale, num_kv=num_kv,
                               block_k=block_k, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rep, D), lambda b, g, kj: (b, g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, G, rep, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*operands)
    return out
