"""Pallas flash attention for TPU.

The hot op of the flagship workload. FlashAttention-2-style streaming softmax
in the canonical TPU grid form: grid = (batch, heads, q_blocks, kv_blocks)
with the kv axis innermost and sequential ("arbitrary"), so each (q_block)
output revisits across kv steps while Pallas double-buffers the K/V block DMAs
HBM→VMEM. Per-program VMEM is O(block_q·d + block_k·d) — long sequences
stream, they never have to fit in VMEM. The running (max, sum, accumulator)
recurrence lives in VMEM scratch that persists across the kv grid steps.
Causal masking skips fully-masked kv blocks' compute via pl.when.

Backward is a pair of FlashAttention-2-style Pallas kernels (no O(s²)
materialization): the forward additionally emits the per-row log-sum-exp
(lane-replicated (b, h, s, 128) float32, the same layout jax's own TPU
kernel uses), the host computes Δ = rowsum(dO ⊙ O), then
- the dKV kernel runs grid (b, h, kv_blocks, q_blocks) with q innermost,
  accumulating dK/dV for its kv block across q blocks in VMEM scratch;
- the dQ kernel runs grid (b, h, q_blocks, kv_blocks) with kv innermost.
Both rebuild P = exp(S − lse) from the residuals (recompute-over-store, the
flash trade), mask causally, and skip fully-masked blocks via pl.when.
On non-TPU backends the kernels run in interpreter mode for tests.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
# per-d_head blocks measured on a real v5e chip (ci/tpu_numerics.py sweep,
# recorded in TPU_NUMERICS.json): 21-28% faster than the generic defaults.
# NOTE: the sweep's top candidates — (256,1024) and (512,1024) for both
# d_heads — flip rank between runs (tunnel timing noise of the same order
# as their gap); any of them is within ~25% of the per-run fastest, so the
# pins below are stable choices, not a per-run argmax.
TUNED_BLOCKS = {64: (256, 1024), 128: (512, 1024)}
_LANES = 128  # per-row stats are stored lane-replicated for (8,128) tiling


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                  scale: float, causal: bool, num_kv: int,
                  with_lse: bool = False):
    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref = None
        m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # (bq, d)
        k = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, bk)
        if causal:
            mask = _causal_mask(qi, kj, block_q, block_k)
            logits = jnp.where(mask, logits, _NEG_INF)
        m_prev = m_scr[:, :1]                                # (bq, 1)
        l_prev = l_scr[:, :1]
        row_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, row_max)
        p = jnp.exp(logits - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)                      # (bq, 1)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[0, 0],
            preferred_element_type=jnp.float32)

    if causal:
        # a kv block right of the diagonal contributes nothing — skip compute
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(compute)
    else:
        compute()

    @pl.when(kj == num_kv - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0, 0] = m_scr[:] + jnp.log(
                jnp.maximum(l_scr[:], 1e-30))


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool, save_residuals: bool = False):
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} must be divisible by blocks "
                         f"({block_q}, {block_k})")
    scale = 1.0 / math.sqrt(d)
    # (b, s, h, d) → (b, h, s, d): the kernel wants (seq, d) as the minor
    # dims (TPU (8,128) tiling); XLA fuses the transposes into neighbors
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    num_kv = s // block_k
    grid = (b, h, s // block_q, num_kv)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               num_kv=num_kv,
                               with_lse=save_residuals)
    out_shape = [jax.ShapeDtypeStruct(qt.shape, q.dtype)]
    out_specs = [pl.BlockSpec((1, 1, block_q, d),
                              lambda bi, hi, qi, kj: (bi, hi, qi, 0))]
    if save_residuals:
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, s, _LANES), jnp.float32))
        out_specs.append(pl.BlockSpec((1, 1, block_q, _LANES),
                                      lambda bi, hi, qi, kj: (bi, hi, qi, 0)))
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, kj: (bi, hi, kj, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, kj: (bi, hi, kj, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running sum
            pltpu.VMEM((block_q, d), jnp.float32),        # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)
    out = outs[0].transpose(0, 2, 1, 3)
    if save_residuals:
        return out, outs[1]
    return out


# ---------------------------------------------------------------- backward
def _causal_mask(qi, kj, block_q, block_k):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_pos >= k_pos


def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    qi, kj, scale: float, causal: bool):
    """Shared FA2 backward math: rebuild P = exp(S − lse) from residuals and
    form dS = P ⊙ (dO·Vᵀ − Δ)·scale. Both backward kernels consume (p, ds,
    q, do) — keeping it in one place keeps dQ consistent with dK/dV."""
    q = q_ref[0, 0].astype(jnp.float32)                  # (bq, d)
    k = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)                # (bq, d)
    lse = lse_ref[0, 0][:, :1]                           # (bq, 1)
    delta = delta_ref[0, 0][:, :1]                       # (bq, 1)
    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # (bq, bk)
    p = jnp.exp(logits - lse)
    if causal:
        block_q, block_k = q.shape[0], k.shape[0]
        p = jnp.where(_causal_mask(qi, kj, block_q, block_k), p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # (bq, bk)
    ds = p * (dp - delta) * scale
    return p, ds, q, do


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *,
                          scale: float, causal: bool, num_q: int):
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    block_k = k_ref.shape[2]
    block_q = q_ref.shape[2]

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute():
        p, ds, q, do = _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                       delta_ref, qi, kj, scale, causal)
        # dV += Pᵀ · dO;  dK += dSᵀ · Q
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)

    if causal:
        # q blocks strictly above the diagonal see none of this kv block
        pl.when(qi * block_q + block_q - 1 >= kj * block_k)(compute)
    else:
        compute()

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *,
                         scale: float, causal: bool, num_kv: int):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]

    @pl.when(kj == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def compute():
        _, ds, _, _ = _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                      delta_ref, qi, kj, scale, causal)
        # dQ += dS · K
        dq_scr[:] += jax.lax.dot(ds, k_ref[0, 0].astype(jnp.float32),
                                 preferred_element_type=jnp.float32)

    if causal:
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(compute)
    else:
        compute()

    @pl.when(kj == num_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal: bool, block_q: int,
                    block_k: int, interpret: bool):
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    scale = 1.0 / math.sqrt(d)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = g.transpose(0, 2, 1, 3)
    ot = o.transpose(0, 2, 1, 3)
    # Δ = rowsum(dO ⊙ O), lane-replicated like lse
    delta = jnp.broadcast_to(
        jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1,
                keepdims=True), (b, h, s, _LANES))
    num_q, num_kv = s // block_q, s // block_k

    q_spec = pl.BlockSpec((1, 1, block_q, d),
                          lambda bi, hi, kj, qi: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d),
                           lambda bi, hi, kj, qi: (bi, hi, kj, 0))
    lane_spec = pl.BlockSpec((1, 1, block_q, _LANES),
                             lambda bi, hi, kj, qi: (bi, hi, qi, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                          num_q=num_q),
        grid=(b, h, num_kv, num_q),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, lane_spec, lane_spec],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct(kt.shape, k.dtype),
                   jax.ShapeDtypeStruct(vt.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    q_spec2 = pl.BlockSpec((1, 1, block_q, d),
                           lambda bi, hi, qi, kj: (bi, hi, qi, 0))
    kv_spec2 = pl.BlockSpec((1, 1, block_k, d),
                            lambda bi, hi, qi, kj: (bi, hi, kj, 0))
    lane_spec2 = pl.BlockSpec((1, 1, block_q, _LANES),
                              lambda bi, hi, qi, kj: (bi, hi, qi, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          num_kv=num_kv),
        grid=(b, h, num_q, num_kv),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, lane_spec2,
                  lane_spec2],
        out_specs=q_spec2,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)
    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                              save_residuals=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    interpret = jax.default_backend() != "tpu"
    return _flash_backward(q, k, v, o, lse, g, causal, block_q, block_k,
                           interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _pick_block(seq_len: int, preferred: int) -> int | None:
    """Largest block ≤ preferred that divides seq_len and respects the TPU
    sublane granularity (multiple of 8, or the whole sequence). None when no
    usable block exists (odd lengths) — callers fall back to XLA attention."""
    for block in range(min(preferred, seq_len), 0, -1):
        if seq_len % block == 0 and (block % 8 == 0 or block == seq_len):
            return block
    return None


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True,
                    block_q: int | None = None,
                    block_k: int | None = None) -> jax.Array:
    """q/k/v: (batch, seq, heads, d_head) → (batch, seq, heads, d_head).
    GQA callers repeat K/V heads before the call (models/transformer.py).
    Unspecified block sizes use the v5e-measured table for the d_head
    (TUNED_BLOCKS) and self-adjust to divide the sequence; sequences with no
    TPU-tileable divisor fall back to the XLA path instead of erroring."""
    s = q.shape[1]
    tuned_q, tuned_k = TUNED_BLOCKS.get(q.shape[3],
                                        (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K))
    bq = _pick_block(s, block_q or tuned_q)
    bk = _pick_block(s, block_k or tuned_k)
    if bq is None or bk is None:
        from ..models.transformer import xla_attention
        return xla_attention(q, k, v, causal=causal)
    return _flash(q, k, v, causal, bq, bk)
