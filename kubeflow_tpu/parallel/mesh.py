"""Device-mesh construction and axis conventions.

The workload layer of the framework: the code that runs *inside* the
containers the control plane provisions (SURVEY §2d — the reference has no
parallelism code; in this framework the TPU provisioning path and this module
together realize it). Axis conventions follow the standard TPU sharding
recipe (mesh → annotate → let XLA insert collectives):

- ``dp``   pure data parallelism (gradients all-reduced over ICI/DCN)
- ``fsdp`` data parallelism with parameter/optimizer sharding (ZeRO-3-style;
           params all-gathered per layer, grads reduce-scattered)
- ``tp``   tensor parallelism (Megatron-style column/row sharded matmuls)
- ``sp``   sequence/context parallelism (ring attention over the seq axis)
- ``pp``   pipeline parallelism (layer stages, microbatched)
- ``ep``   expert parallelism (MoE experts spread over devices)

Multi-host: the controller injects TPU_WORKER_ID/TPU_WORKER_HOSTNAMES
(controllers/notebook.py) and runtime.bootstrap turns those into a
jax.distributed world; this module only sees the resulting global device list.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "fsdp", "pp", "sp", "tp", "ep")


@dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. Any axis set to 1 is still present in the Mesh (a
    size-1 axis costs nothing under XLA) so PartitionSpecs are config-independent."""
    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.pp * self.sp * self.tp * self.ep

    def axis_sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXES}

    @staticmethod
    def auto(n_devices: int, *, tp: int = 1, sp: int = 1, pp: int = 1,
             ep: int = 1, fsdp: int | None = None) -> "MeshConfig":
        """Fill the data axes with whatever devices remain after the model
        axes are chosen. fsdp defaults to all remaining devices (the usual
        TPU recipe: fsdp within a slice, dp across slices)."""
        model = tp * sp * pp * ep
        if n_devices % model:
            raise ValueError(f"model axes tp*sp*pp*ep={model} do not divide "
                             f"device count {n_devices}")
        remaining = n_devices // model
        if fsdp is None:
            fsdp = remaining
        if remaining % fsdp:
            raise ValueError(f"fsdp={fsdp} does not divide remaining "
                             f"{remaining} devices")
        return MeshConfig(dp=remaining // fsdp, fsdp=fsdp, pp=pp, sp=sp,
                          tp=tp, ep=ep)


def build_mesh(config: MeshConfig, devices=None) -> Mesh:
    """Build a named Mesh.

    Axis order matters for ICI locality: the innermost (fastest-varying)
    axes should carry the heaviest collectives. Device order from
    jax.devices() follows the physical torus, so we place ``tp`` innermost
    (all-reduce per layer), then ``sp`` (ring permutes), then ``pp``
    (point-to-point), with the data axes outermost (one gradient
    reduction per step — fine over DCN)."""
    if devices is None:
        devices = jax.devices()
    if config.size != len(devices):
        raise ValueError(
            f"mesh of size {config.size} ({config.axis_sizes()}) != "
            f"{len(devices)} devices")
    shape = tuple(getattr(config, a) for a in AXES)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def factor_devices(n: int) -> MeshConfig:
    """Heuristic mesh for quick-start: tp up to 4 if it divides, rest fsdp."""
    tp = math.gcd(n, 4)
    return MeshConfig.auto(n, tp=tp)
