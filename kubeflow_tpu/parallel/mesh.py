"""Device-mesh construction and axis conventions.

The workload layer of the framework: the code that runs *inside* the
containers the control plane provisions (SURVEY §2d — the reference has no
parallelism code; in this framework the TPU provisioning path and this module
together realize it). Axis conventions follow the standard TPU sharding
recipe (mesh → annotate → let XLA insert collectives):

- ``dp``   pure data parallelism (gradients all-reduced over ICI/DCN)
- ``fsdp`` data parallelism with parameter/optimizer sharding (ZeRO-3-style;
           params all-gathered per layer, grads reduce-scattered)
- ``tp``   tensor parallelism (Megatron-style column/row sharded matmuls)
- ``sp``   sequence/context parallelism (ring attention over the seq axis)
- ``pp``   pipeline parallelism (layer stages, microbatched)
- ``ep``   expert parallelism (MoE experts spread over devices)

Multi-host: the controller injects TPU_WORKER_ID/TPU_WORKER_HOSTNAMES
(controllers/notebook.py) and runtime.bootstrap turns those into a
jax.distributed world; this module only sees the resulting global device list.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "fsdp", "pp", "sp", "tp", "ep")


@dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. Any axis set to 1 is still present in the Mesh (a
    size-1 axis costs nothing under XLA) so PartitionSpecs are config-independent."""
    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.pp * self.sp * self.tp * self.ep

    def axis_sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXES}

    @staticmethod
    def auto(n_devices: int, *, tp: int = 1, sp: int = 1, pp: int = 1,
             ep: int = 1, fsdp: int | None = None) -> "MeshConfig":
        """Fill the data axes with whatever devices remain after the model
        axes are chosen. fsdp defaults to all remaining devices (the usual
        TPU recipe: fsdp within a slice, dp across slices)."""
        model = tp * sp * pp * ep
        if n_devices % model:
            raise ValueError(f"model axes tp*sp*pp*ep={model} do not divide "
                             f"device count {n_devices}")
        remaining = n_devices // model
        if fsdp is None:
            fsdp = remaining
        if remaining % fsdp:
            raise ValueError(f"fsdp={fsdp} does not divide remaining "
                             f"{remaining} devices")
        return MeshConfig(dp=remaining // fsdp, fsdp=fsdp, pp=pp, sp=sp,
                          tp=tp, ep=ep)


def build_mesh(config: MeshConfig, devices=None) -> Mesh:
    """Build a named Mesh.

    Axis order matters for ICI locality: the innermost (fastest-varying)
    axes should carry the heaviest collectives. Device order from
    jax.devices() follows the physical torus, so we place ``tp`` innermost
    (all-reduce per layer), then ``sp`` (ring permutes), then ``pp``
    (point-to-point), with the data axes outermost (one gradient
    reduction per step — fine over DCN)."""
    if devices is None:
        devices = jax.devices()
    if config.size != len(devices):
        raise ValueError(
            f"mesh of size {config.size} ({config.axis_sizes()}) != "
            f"{len(devices)} devices")
    shape = tuple(getattr(config, a) for a in AXES)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def factor_devices(n: int) -> MeshConfig:
    """Heuristic mesh for quick-start: tp up to 4 if it divides, rest fsdp."""
    tp = math.gcd(n, 4)
    return MeshConfig.auto(n, tp=tp)


# ------------------------------------------------------- multi-slice (DCN)
def group_by_slice(devices) -> list[list]:
    """Group devices by their TPU slice. Real multi-slice TPU devices carry
    ``slice_index``; devices without it (CPU, single slice) land in one
    group. Groups are ordered by slice index; within a group the caller's
    device order is preserved (like build_mesh — callers may pass a
    torus-ordered list from mesh_utils)."""
    groups: dict[int, list] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0), []).append(d)
    return [groups[k] for k in sorted(groups)]


def build_hybrid_mesh(n_slices: int, per_slice: MeshConfig,
                      devices=None) -> tuple[Mesh, MeshConfig]:
    """Multi-slice mesh: ``dp`` spans slices (DCN), every other axis stays
    inside a slice (ICI).

    This is the sharding-recipe shape for TPU multislice: the only
    per-step cross-slice traffic is the gradient all-reduce on ``dp``,
    which tolerates DCN latency, while fsdp all-gathers, tp all-reduces,
    sp ring permutes, and ep all-to-alls ride the intra-slice torus
    (mesh_utils.create_hybrid_device_mesh encodes the same rule; this
    builder additionally works with explicit/virtual device lists, where
    devices are chunked into equal contiguous slices).

    Returns (mesh, full_config) — the full config is ``per_slice`` with
    ``dp`` multiplied by ``n_slices``, usable anywhere a MeshConfig is.
    """
    if devices is None:
        devices = jax.devices()
    total = n_slices * per_slice.size
    if len(devices) != total:
        raise ValueError(f"{n_slices} slices × per-slice size "
                         f"{per_slice.size} != {len(devices)} devices")
    groups = group_by_slice(devices)
    if len(groups) == 1 and n_slices > 1:
        # virtual/CPU devices carry no slice_index: chunk contiguously
        flat = groups[0]
        groups = [flat[i * per_slice.size:(i + 1) * per_slice.size]
                  for i in range(n_slices)]
    if len(groups) != n_slices:
        raise ValueError(f"devices span {len(groups)} slices, expected "
                         f"{n_slices}")
    for i, g in enumerate(groups):
        if len(g) != per_slice.size:
            raise ValueError(
                f"slice {i} has {len(g)} devices, per-slice mesh needs "
                f"{per_slice.size} ({per_slice.axis_sizes()})")
    per_shape = tuple(getattr(per_slice, a) for a in AXES)
    slice_arrays = [np.asarray(g).reshape(per_shape) for g in groups]
    # stack along dp: (n_slices * per_dp, fsdp, pp, sp, tp, ep)
    arr = np.concatenate(slice_arrays, axis=0)
    full = MeshConfig(dp=n_slices * per_slice.dp, fsdp=per_slice.fsdp,
                      pp=per_slice.pp, sp=per_slice.sp, tp=per_slice.tp,
                      ep=per_slice.ep)
    return Mesh(arr, AXES), full
