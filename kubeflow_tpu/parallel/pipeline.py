"""Pipeline parallelism: GPipe-style microbatched layer stages over ``pp``.

Stages are laid out on the ``pp`` mesh axis; activations hop stage→stage with
lax.ppermute (point-to-point over ICI neighbors, not all-to-all), while the
other mesh axes (dp/fsdp/tp) stay in GSPMD "auto" mode inside the stage body —
shard_map is manual over ``pp`` only (``axis_names={'pp'}``), so per-stage
matmuls keep their tensor-parallel shardings without hand-written collectives.

Schedule: plain GPipe fill-and-drain — T = n_micro + n_stages - 1 ticks, each
tick every stage runs its layer block on its current microbatch and permutes
the result forward. Bubble fraction (S-1)/T shrinks with more microbatches.
The whole schedule is a lax.fori_loop: one traced tick, differentiable end to
end (ppermute and the masked buffer writes all have transpose rules, so the
backward pass pipelines in reverse automatically).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def split_stages(stacked_params, n_stages: int):
    """Reshape a layer-stacked param tree (L, ...) → (n_stages, L/S, ...).
    The leading stage axis is what ``pp`` shards."""
    def reshape(p):
        L = p.shape[0]
        if L % n_stages:
            raise ValueError(f"{L} layers not divisible into {n_stages} stages")
        return p.reshape(n_stages, L // n_stages, *p.shape[1:])
    return jax.tree.map(reshape, stacked_params)


def pipeline_apply(stage_params, x: jax.Array, stage_fn, *, mesh: Mesh,
                   n_microbatches: int, manual_axes: tuple = ("pp",),
                   act_spec: P = P(), extra_args: tuple = (),
                   extra_specs: tuple = ()) -> jax.Array:
    """Run ``stage_fn(stage_params_i, activation, *extra) -> activation``
    through the pp ring. ``x``: (batch, ...) activations entering stage 0;
    returns stage S-1's output, replicated over pp. Activation shape must
    be uniform across stages (true for transformer blocks).

    ``manual_axes`` extends the manual region beyond pp — pass
    ``("pp", "sp")`` with ``act_spec`` sharding the sequence axis to run
    sequence-parallel stage bodies (ring attention via bare ppermute over
    sp, see models/transformer.pipelined_forward). ``extra_args`` are
    broadcast to every tick (e.g. RoPE tables), split per
    ``extra_specs``.

    ``x`` may be a PYTREE of (batch, ...) arrays — e.g. the MoE stage
    carries {activation, per-microbatch aux-loss accumulator}; every leaf
    hops the ring together. ``act_spec`` applies to every leaf (ranks
    permitting), so pytree activations compose with pp but not (yet)
    with a sequence-sharded act_spec."""
    # NOTE: partial-manual shard_map (axis_names={'pp', ...}) requires a
    # jit context — call this from inside jit (the train step always is).
    n_stages = mesh.shape["pp"]
    if n_stages == 1:
        params0 = jax.tree.map(lambda p: p[0], stage_params)
        return stage_fn(params0, x, *extra_args)
    leaves = jax.tree.leaves(x)
    batch = leaves[0].shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible by "
                         f"{n_microbatches} microbatches")
    mb = batch // n_microbatches
    micro = jax.tree.map(
        lambda a: a.reshape(n_microbatches, mb, *a.shape[1:]), x)
    micro_spec = P(None, *act_spec)  # leading microbatch axis: unsharded
    micro_specs = jax.tree.map(lambda _: micro_spec, x)

    @partial(shard_map, mesh=mesh, axis_names=set(manual_axes),
             in_specs=(P("pp"), micro_specs, *extra_specs),
             out_specs=micro_specs, check_vma=False)
    def run(params_local, micro_all, *extra):
        # params_local leaves: (1, L/S, ...) — drop the sharded stage axis
        params_local = jax.tree.map(lambda p: p[0], params_local)
        stage = lax.axis_index("pp")
        last = n_stages - 1
        ticks = n_microbatches + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        state = jax.tree.map(lambda m: jnp.zeros_like(m[0]), micro_all)
        out_buf = jax.tree.map(jnp.zeros_like, micro_all)

        def tick(t, carry):
            state, out_buf = carry
            in_idx = jnp.clip(t, 0, n_microbatches - 1)
            inp = jax.tree.map(
                lambda m, s: jnp.where(stage == 0, m[in_idx], s),
                micro_all, state)
            out = stage_fn(params_local, inp, *extra)
            out_idx = t - last
            safe_idx = jnp.clip(out_idx, 0, n_microbatches - 1)
            take = jnp.logical_and(stage == last, out_idx >= 0)
            out_buf = jax.tree.map(
                lambda buf, o: jnp.where(take, buf.at[safe_idx].set(o),
                                         buf),
                out_buf, out)
            state = jax.tree.map(lambda o: lax.ppermute(o, "pp", perm), out)
            return state, out_buf

        _, out_buf = lax.fori_loop(0, ticks, tick, (state, out_buf),
                                   unroll=False)
        # replicate the last stage's result to every pp rank
        return jax.tree.map(
            lambda buf: lax.psum(jnp.where(stage == last, buf, 0.0), "pp"),
            out_buf)

    y = run(stage_params, micro, *extra_args)
    return jax.tree.map(
        lambda buf, orig: buf.reshape(batch, *orig.shape[1:]), y, x)
