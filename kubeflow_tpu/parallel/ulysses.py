"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second long-context scheme (SURVEY preamble: "ring attention OR
all-to-all sequence/context parallelism"), complementing parallel/ring.py.
Where ring attention keeps the sequence sharded and rotates K/V around the
``sp`` ring (sp ppermutes of the K/V blocks per layer), Ulysses trades two
all-to-alls for fully local attention: scatter heads / gather sequence, run
the exact attention kernel on the full sequence with heads/sp heads per
device, then scatter sequence / gather heads back. Communication volume per
device is O(seq/sp · d · heads) per all-to-all, independent of sp — usually
cheaper than ring on meshes where sp is large and heads are plentiful, while
ring wins when heads/sp would not divide or the per-device full-seq logits
would not fit.

Technique after Jacobs et al., "DeepSpeed Ulysses" (arXiv:2309.14509);
implementation is original, built on shard_map + lax.all_to_all.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      mesh: Mesh | None, axis_name: str = "sp",
                      causal: bool = True, n_rep: int = 1) -> jax.Array:
    """Global-view Ulysses attention. q: (batch, seq, heads, d_head), k/v:
    (batch, seq, heads/n_rep, d_head) — GQA callers pass the UN-repeated
    K/V plus ``n_rep`` so the K/V all-to-alls move 1/n_rep the bytes; the
    repeat happens after the exchange (chunk-aligned because consecutive-head
    repeat and the head split commute). Sequence is sharded over
    ``axis_name``; returns q's shape/sharding.

    The per-device q head count (heads already divided by tp) must be
    divisible by the ``sp`` axis size. Callable inside jit. Falls back to
    local attention when no mesh is in play (decode prefill and pipeline
    stages call attention with mesh=None)."""
    sp = mesh.shape[axis_name] if mesh is not None else 1
    if sp == 1:
        from ..models.transformer import repeat_kv, xla_attention
        return xla_attention(q, repeat_kv(k, n_rep), repeat_kv(v, n_rep),
                             causal=causal)

    tp = mesh.shape.get("tp", 1)
    heads_local = q.shape[2] // tp
    if heads_local % sp:
        raise ValueError(
            f"ulysses needs per-device heads ({q.shape[2]}/tp={heads_local}) "
            f"divisible by sp={sp}; use ring attention for this shape")
    if k.shape[2] % tp:
        # kv heads don't divide tp (possible with aggressive GQA): repeat
        # K/V up to q's head count BEFORE sharding so the tp split holds —
        # full-width exchange, correctness over the bandwidth saving
        from ..models.transformer import repeat_kv
        k, v = repeat_kv(k, n_rep), repeat_kv(v, n_rep)
        n_rep = 1
    kv_heads_local = k.shape[2] // tp
    # exchange-then-repeat only when the kv head chunks stay aligned
    repeat_after = n_rep > 1 and kv_heads_local % sp == 0

    spec = P(("dp", "fsdp"), axis_name, "tp", None)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def _ulysses(q_blk, k_blk, v_blk):
        from ..models.transformer import repeat_kv

        # (b, s/sp, h, d) → (b, s, h/sp, d): scatter heads, gather sequence
        def fwd(x):
            return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

        if not repeat_after:
            k_in, v_in = repeat_kv(k_blk, n_rep), repeat_kv(v_blk, n_rep)
        else:
            k_in, v_in = k_blk, v_blk
        qf, kf, vf = fwd(q_blk), fwd(k_in), fwd(v_in)
        if repeat_after:
            kf, vf = repeat_kv(kf, n_rep), repeat_kv(vf, n_rep)
        if jax.default_backend() == "tpu":
            from ..ops.attention import flash_attention
            out = flash_attention(qf, kf, vf, causal=causal)
        else:
            from ..models.transformer import xla_attention
            out = xla_attention(qf, kf, vf, causal=causal)
        # (b, s, h/sp, d) → (b, s/sp, h, d): scatter sequence, gather heads
        return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    return _ulysses(q, k, v)
