"""Regex partition rules: tree-path patterns → PartitionSpecs for ANY pytree.

The logical-axis rules in parallel/sharding.py need every model family to
hand-write a spec tree (param_logical_specs / moe_param_logical_specs) and
every optimizer wrapper to mirror it (opt_state_shardings). The elastic
trainer cannot afford that coupling: on every shrink/grow it must re-shard
whatever pytree the user trains — params, optax state, bf16 master copies —
onto a mesh it just rebuilt. This module is the EasyLM-style alternative:
an ordered list of ``(regex, PartitionSpec)`` rules matched (``re.search``,
first match wins) against the '/'-joined tree path of each leaf, so one
rule table shards the param tree AND any optimizer state embedding it (an
adamw ``mu/blocks/wq`` path ends with the same suffix as the param's
``blocks/wq``). Scalars and size-1 leaves replicate unconditionally.

``TRANSFORMER_RULES`` / ``MOE_RULES`` reproduce the hand specs exactly —
tests/test_partition_rules.py pins the equivalence against
param_logical_specs on stock configs — and the per-family split exists
because one table cannot serve both: dense ``w_gate`` is
(layers, embed, mlp) where MoE ``w_gate`` is (layers, experts, embed, mlp).
"""

from __future__ import annotations

import re

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.tree_util import (DictKey, FlattenedIndexKey, GetAttrKey,
                           SequenceKey, tree_flatten_with_path,
                           tree_unflatten)

# Megatron TP + ZeRO-3 FSDP, matching DEFAULT_RULES in sharding.py:
#   column-parallel weights shard their output dim on tp, row-parallel
#   their input dim on tp, the other big dim on fsdp; norms/head_dim/
#   layers replicate; the embedding table puts vocab on tp.
TRANSFORMER_RULES: tuple[tuple[str, P], ...] = (
    (r"w[qkv]$", P(None, "fsdp", "tp", None)),
    (r"wo$", P(None, "tp", None, "fsdp")),
    (r"(w_gate|w_up)$", P(None, "fsdp", "tp")),
    (r"w_down$", P(None, "tp", "fsdp")),
    (r"(attn_norm|mlp_norm)$", P(None, None)),
    (r"final_norm$", P(None)),
    (r"lm_head$", P("fsdp", "tp")),
    (r"embed$", P("tp", "fsdp")),
)

# MoE: expert MLPs gain a leading experts axis (→ ep); the router projects
# embed → n_experts. Attention/embedding/norm rules are shared with dense.
MOE_RULES: tuple[tuple[str, P], ...] = (
    (r"w[qkv]$", P(None, "fsdp", "tp", None)),
    (r"wo$", P(None, "tp", None, "fsdp")),
    (r"router$", P(None, "fsdp", "ep")),
    (r"(w_gate|w_up)$", P(None, "ep", "fsdp", "tp")),
    (r"w_down$", P(None, "ep", "tp", "fsdp")),
    (r"(attn_norm|mlp_norm)$", P(None, None)),
    (r"final_norm$", P(None)),
    (r"lm_head$", P("fsdp", "tp")),
    (r"embed$", P("tp", "fsdp")),
)


def rules_for(config) -> tuple[tuple[str, P], ...]:
    """Rule table for a model config (MoEConfig subclasses dense)."""
    from ..models.moe import MoEConfig
    return MOE_RULES if isinstance(config, MoEConfig) else TRANSFORMER_RULES


def _key_str(key) -> str:
    if isinstance(key, DictKey):
        return str(key.key)
    if isinstance(key, GetAttrKey):
        return key.name
    if isinstance(key, SequenceKey):
        return str(key.idx)
    if isinstance(key, FlattenedIndexKey):
        return str(key.key)
    return str(key)


def tree_path_of(path) -> str:
    """'/'-joined name of one leaf's key path: ('blocks','wq') → 'blocks/wq',
    and an optimizer path like (0, 'mu', 'blocks', 'wq') →
    '0/mu/blocks/wq' — the suffix the rules anchor on."""
    return "/".join(_key_str(k) for k in path)


def _leaf_dims(leaf) -> tuple[int, int]:
    """(ndim, size) for arrays AND abstract leaves (ShapeDtypeStruct)."""
    shape = tuple(getattr(leaf, "shape", ()))
    return len(shape), int(np.prod(shape)) if shape else 1


def match_partition_rules(rules, tree):
    """Pytree of PartitionSpecs, same structure as ``tree``. Scalars and
    size-1 leaves get P() (replicated — sharding a singleton buys nothing
    and a rule written for the full-size tensor would over-constrain it);
    every other leaf must match a rule or the call raises, because a
    silently-replicated large tensor is exactly the OOM a partition-rule
    engine exists to prevent."""
    leaves, treedef = tree_flatten_with_path(tree)
    specs = []
    for path, leaf in leaves:
        ndim, size = _leaf_dims(leaf)
        if ndim == 0 or size == 1:
            specs.append(P())
            continue
        name = tree_path_of(path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                specs.append(spec)
                break
        else:
            raise ValueError(f"no partition rule matches leaf {name!r} "
                             f"(shape {tuple(leaf.shape)})")
    return tree_unflatten(treedef, specs)


def named_shardings(mesh: Mesh, spec_tree):
    """PartitionSpec tree → NamedSharding tree on ``mesh``."""
    import jax
    return jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                        is_leaf=lambda x: isinstance(x, P))


def make_shard_and_gather_fns(mesh: Mesh, spec_tree):
    """Per-leaf (shard_fns, gather_fns) trees: ``shard`` lays a host/
    replicated leaf out on ``mesh`` per its rule spec, ``gather`` pulls it
    back fully replicated — both jitted identities whose out_shardings do
    the data movement (XLA inserts the collectives)."""
    import jax

    def shard_fn(spec):
        return jax.jit(lambda x: x,
                       out_shardings=NamedSharding(mesh, spec))

    def gather_fn(spec):
        return jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))

    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    return (jax.tree.map(shard_fn, spec_tree, is_leaf=is_spec),
            jax.tree.map(gather_fn, spec_tree, is_leaf=is_spec))
