"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context support (SURVEY preamble: "ring attention or all-to-all
sequence/context parallelism for long sequences" is first-class). Each device
holds one sequence block of Q/K/V; K/V blocks rotate around the ``sp`` ring
via lax.ppermute (XLA collective-permute rides the ICI torus) while each
device accumulates its Q-block's attention with the numerically-stable
streaming-softmax (flash) recurrence. Memory per device is O(seq/sp · seq/sp)
per step instead of O(seq²), and compute/communication overlap is left to
XLA's async collectives.

Technique after Liu et al., "Ring Attention with Blockwise Transformers"
(arXiv:2310.01889); implementation is original, built on shard_map + ppermute.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

_NEG_INF = -1e30


def _block_attention(q, k, v, *, scale, mask):
    """One (q-block × kv-block) flash step. q,k,v: (b, s, h, d);
    mask: (sq, sk) bool or None. Returns (contrib, row_sum, row_max) where
    contrib = exp(logits - row_max) @ v."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask[None, None, :, :], logits, _NEG_INF)
    row_max = jnp.max(logits, axis=-1)                       # (b, h, sq)
    p = jnp.exp(logits - row_max[..., None])
    if mask is not None:
        p = p * mask[None, None, :, :]
    row_sum = jnp.sum(p, axis=-1)                            # (b, h, sq)
    contrib = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return contrib, row_sum, row_max


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh | None, axis_name: str = "sp",
                   causal: bool = True) -> jax.Array:
    """Global-view ring attention. q/k/v: (batch, seq, heads, d_head) with
    seq sharded over ``axis_name``; returns same shape/sharding as q.

    Callable inside jit; shard_map handles the global→per-device view.
    Falls back to local attention when no mesh is in play (decode prefill
    and pipeline stages call attention with mesh=None)."""
    sp = mesh.shape[axis_name] if mesh is not None else 1
    if sp == 1:
        from ..models.transformer import xla_attention
        return xla_attention(q, k, v, causal=causal)

    batch_axes = ("dp", "fsdp")
    spec_q = P(batch_axes, axis_name, "tp", None)

    @partial(shard_map, mesh=mesh, in_specs=(spec_q, spec_q, spec_q),
             out_specs=spec_q, check_vma=False)
    def _ring(q_blk, k_blk, v_blk):
        return _ring_local(q_blk, k_blk, v_blk, axis_name=axis_name,
                           axis_size=sp, causal=causal)

    return _ring(q, k, v)


def _ring_local(q, k, v, *, axis_name: str, axis_size: int, causal: bool):
    """Per-device body: rotate K/V around the ring, accumulate flash stats."""
    b, sq, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    my_idx = lax.axis_index(axis_name)
    q32 = q  # keep input dtype for matmuls; stats in f32

    o = jnp.zeros((b, sq, h, d), jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    m = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    q_pos = my_idx * sq + jnp.arange(sq)

    def step(t, carry):
        o, l, m, k_cur, v_cur = carry

        def attend(operand):
            o, l, m, k_cur, v_cur, kv_idx = operand
            if causal:
                k_pos = kv_idx * sq + jnp.arange(sq)
                mask = q_pos[:, None] >= k_pos[None, :]
            else:
                mask = jnp.ones((sq, sq), bool)
            contrib, row_sum, row_max = _block_attention(
                q32, k_cur, v_cur, scale=scale, mask=mask)
            m_new = jnp.maximum(m, row_max)
            alpha = jnp.exp(m - m_new)        # rescale of old accumulator
            beta = jnp.exp(row_max - m_new)   # rescale of this block
            l_new = l * alpha + row_sum * beta
            o_new = (o * alpha.transpose(0, 2, 1)[..., None]
                     + contrib.astype(jnp.float32)
                     * beta.transpose(0, 2, 1)[..., None])
            return o_new, l_new, m_new

        kv_idx = (my_idx - t) % axis_size
        if causal:
            # blocks strictly above the diagonal are fully masked — skip the
            # matmuls entirely (≈ halves causal FLOPs; the cond is local
            # per-device compute, the ppermute below stays unconditional so
            # the collective schedule is uniform across the ring)
            o, l, m = lax.cond(kv_idx <= my_idx, attend,
                               lambda operand: (operand[0], operand[1],
                                                operand[2]),
                               (o, l, m, k_cur, v_cur, kv_idx))
        else:
            o, l, m = attend((o, l, m, k_cur, v_cur, kv_idx))
        # rotate kv to the next ring member (device i → i+1)
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o, l, m, k_nxt, v_nxt

    o, l, m, _, _ = lax.fori_loop(0, axis_size, step, (o, l, m, k, v))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)
