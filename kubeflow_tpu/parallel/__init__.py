from .mesh import MeshConfig, build_mesh, AXES
from .sharding import (batch_sharding, named_sharding, param_shardings,
                       PartitionRules)

__all__ = ["MeshConfig", "build_mesh", "AXES", "batch_sharding",
           "named_sharding", "param_shardings", "PartitionRules"]
