"""Partition rules: logical axis names → mesh axes → NamedShardings.

The standard TPU recipe (annotate shardings, let XLA/GSPMD insert the
collectives) rather than hand-written NCCL calls. Rules map *logical* tensor
axes ("vocab", "embed", "mlp", "heads", "batch", "seq", "layers", "experts")
to mesh axes, so models declare intent once and any MeshConfig lays it out."""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# Default rules — Megatron-style TP + ZeRO-3 FSDP + sequence parallelism:
#   column-parallel weights shard their output dim on tp, row-parallel their
#   input dim on tp; the other big dim is sharded on fsdp (param gathering);
#   batch shards over (dp, fsdp); sequence over sp; experts over ep.
DEFAULT_RULES: tuple[tuple[str, str | tuple | None], ...] = (
    ("vocab", "tp"),
    ("embed", "fsdp"),
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("head_dim", None),
    ("mlp", "tp"),
    ("experts", "ep"),
    ("batch", ("dp", "fsdp")),
    ("seq", "sp"),
    ("layers", None),
    ("stages", "pp"),
    ("norm", None),
)


@dataclass
class PartitionRules:
    rules: tuple = DEFAULT_RULES

    def spec(self, *logical_axes: str | None) -> P:
        mapping = dict(self.rules)
        out = []
        for ax in logical_axes:
            if ax is None:
                out.append(None)
            else:
                if ax not in mapping:
                    raise KeyError(f"no partition rule for logical axis {ax!r}")
                out.append(mapping[ax])
        return P(*out)

    def sharding(self, mesh: Mesh, *logical_axes: str | None) -> NamedSharding:
        return NamedSharding(mesh, self.spec(*logical_axes))


def named_sharding(mesh: Mesh, *axes) -> NamedSharding:
    return NamedSharding(mesh, P(*axes))


def batch_sharding(mesh: Mesh, accum: bool = False) -> NamedSharding:
    """Input batches shard over the data axes and sequence axis; with
    ``accum`` the leading microbatch axis stays unsharded (scanned)."""
    if accum:
        return NamedSharding(mesh, P(None, ("dp", "fsdp"), "sp"))
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp"))


def param_shardings(mesh: Mesh, param_specs, rules: PartitionRules | None = None):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    rules = rules or PartitionRules()
    return jax.tree.map(
        lambda spec: rules.sharding(mesh, *spec),
        param_specs,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x),
    )


def constrain(x, mesh: Mesh, *axes):
    """with_sharding_constraint shorthand used inside jitted model code to
    pin activation layouts (e.g. re-shard after attention)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*axes)))
