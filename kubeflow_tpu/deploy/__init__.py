from .manifests import (generate_all, notebook_crd, render_kustomize_tree,
                        write_tree)

__all__ = ["generate_all", "notebook_crd", "render_kustomize_tree",
           "write_tree"]
