"""Deployment-manifest generation: CRD + kustomize tree.

The reference ships a generated CRD
(config/crd/bases/kubeflow.org_notebooks.yaml, 11,650 lines produced by
controller-gen from the Go types) plus a kustomize layout per controller:
bases (crd/manager/rbac/webhook), a ``default`` composition, and overlays
(kubeflow: Istio on; openshift: culler ConfigMap + USE_ISTIO=false +
ADD_FSGROUP=false; standalone) — notebook-controller/config/* — and for the
extension controller a ``params.env`` image/flag pinning wired into the
Deployment through kustomize replacements (odh config/base/kustomization.yaml).
CI regenerates and diffs to catch drift (ci/generate_code.sh:1-12).

Here the single source of truth is the Python API layer: this module renders
the CRD schema and every deployment object from the same constants the
controllers use (api.types, utils.names, utils.config), and
``ci/generate_manifests.py`` writes the tree under ``config/``; a pytest
drift check regenerates and compares, replacing the reference's CI shell
diff. The spec keeps the reference's wire shape — ``spec.template.spec`` is a
full PodSpec (pruned-but-preserved, x-kubernetes-preserve-unknown-fields) —
so existing Notebook CRs apply unchanged; TPU topology rides on annotations
(tpu.kubeflow.org/accelerator, .../topology).
"""

from __future__ import annotations

import io
from pathlib import Path

import yaml

from ..api import schema
from ..api import types as api
from ..utils import names

MANAGER_IMAGE_PARAM = "kubeflow-tpu-notebook-controller"
DEFAULT_MANAGER_IMAGE = \
    "us-docker.pkg.dev/kubeflow-tpu/notebook-controller:latest"
NAMESPACE = "kubeflow-tpu-system"
CRD_NAME = f"notebooks.{api.GROUP}"


# ----------------------------------------------------------------------- CRD

def _condition_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "type": {"type": "string"},
            "status": {"type": "string"},
            "reason": {"type": "string"},
            "message": {"type": "string"},
            "lastProbeTime": {"type": "string", "format": "date-time"},
            "lastTransitionTime": {"type": "string", "format": "date-time"},
        },
        "required": ["type", "status"],
    }


def _notebook_schema() -> dict:
    """The storage schema: spec wraps a PodSpec template (reference
    api/v1beta1/notebook_types.go:27-34 — ``Template{Spec corev1.PodSpec}``)
    with the pod spec TYPED on every field the controllers touch
    (api/schema.py's maintained subset standing in for the reference's
    11k-line generated expansion) so a malformed container is rejected
    server-side; semantic validation beyond structure stays in the
    validating webhook, where it can say WHY something is rejected."""
    return {
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "spec": {
                    "type": "object",
                    "required": ["template"],
                    "properties": {
                        "template": {
                            "type": "object",
                            "required": ["spec"],
                            "properties": {
                                "spec": schema.pod_spec_schema(),
                            },
                        },
                    },
                },
                "status": {
                    "type": "object",
                    "properties": {
                        "conditions": {"type": "array",
                                       "items": _condition_schema()},
                        "readyReplicas": {"type": "integer",
                                          "format": "int32"},
                        "containerState": {
                            "type": "object",
                            "x-kubernetes-preserve-unknown-fields": True,
                        },
                    },
                },
            },
        },
    }


def notebook_crd() -> dict:
    """CustomResourceDefinition with v1 as storage version and served
    v1beta1/v1alpha1 sharing the identical schema — the reference serves all
    three with v1 as storage (api/v1/notebook_types.go:67-68)."""
    versions = []
    for version in api.SERVED_VERSIONS:
        versions.append({
            "name": version,
            "served": True,
            "storage": version == api.STORAGE_VERSION,
            "schema": _notebook_schema(),
            "subresources": {"status": {}},
            "additionalPrinterColumns": [
                {"name": "Ready", "type": "string",
                 "jsonPath": ".status.conditions[?(@.type=='SliceReady')].status"},
                {"name": "Age", "type": "date",
                 "jsonPath": ".metadata.creationTimestamp"},
            ],
        })
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": CRD_NAME},
        "spec": {
            "group": api.GROUP,
            "names": {"kind": api.KIND, "listKind": "NotebookList",
                      "plural": "notebooks", "singular": "notebook"},
            "scope": "Namespaced",
            "versions": versions,
        },
    }


def slicepool_crd() -> dict:
    """CustomResourceDefinition for the warm slice pool (tpu.kubeflow.org/v1
    SlicePool, cluster-scoped — controllers/slicepool.py). Single served
    version; no reference analog."""
    from ..api import slicepool
    schema_doc = {
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "spec": {
                    "type": "object",
                    "required": ["accelerator", "warmReplicas"],
                    "properties": {
                        "accelerator": {"type": "string"},
                        "warmReplicas": {"type": "integer",
                                         "format": "int32", "minimum": 0},
                        "namespace": {"type": "string"},
                        "weights": {
                            "type": "object",
                            "additionalProperties": {"type": "integer",
                                                     "minimum": 1},
                        },
                    },
                },
                "status": {
                    "type": "object",
                    "properties": {
                        "warm": {"type": "integer", "format": "int32"},
                        "warming": {"type": "integer", "format": "int32"},
                        "bound": {"type": "integer", "format": "int32"},
                        "pending": {"type": "integer", "format": "int32"},
                    },
                },
            },
        },
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{slicepool.PLURAL}.{slicepool.GROUP}"},
        "spec": {
            "group": slicepool.GROUP,
            "names": {"kind": slicepool.KIND, "listKind": "SlicePoolList",
                      "plural": slicepool.PLURAL, "singular": "slicepool"},
            "scope": "Cluster",
            "versions": [{
                "name": slicepool.VERSION,
                "served": True,
                "storage": True,
                "schema": schema_doc,
                "subresources": {"status": {}},
                "additionalPrinterColumns": [
                    {"name": "Accelerator", "type": "string",
                     "jsonPath": ".spec.accelerator"},
                    {"name": "Target", "type": "integer",
                     "jsonPath": ".spec.warmReplicas"},
                    {"name": "Warm", "type": "integer",
                     "jsonPath": ".status.warm"},
                    {"name": "Bound", "type": "integer",
                     "jsonPath": ".status.bound"},
                ],
            }],
        },
    }


def tpuquota_crd() -> dict:
    """CustomResourceDefinition for per-tenant slice quota
    (tpu.kubeflow.org/v1 TPUQuota, cluster-scoped — the scheduler's
    admission ceiling, controllers/scheduler.py). Single served version;
    no reference analog."""
    from ..api import tpuquota
    schema_doc = {
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "spec": {
                    "type": "object",
                    "required": ["tenant", "maxSlices"],
                    "properties": {
                        "tenant": {"type": "string"},
                        "maxSlices": {"type": "integer",
                                      "format": "int32", "minimum": 0},
                    },
                },
                "status": {
                    "type": "object",
                    "properties": {
                        "used": {"type": "integer", "format": "int32"},
                    },
                },
            },
        },
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{tpuquota.PLURAL}.{tpuquota.GROUP}"},
        "spec": {
            "group": tpuquota.GROUP,
            "names": {"kind": tpuquota.KIND, "listKind": "TPUQuotaList",
                      "plural": tpuquota.PLURAL, "singular": "tpuquota"},
            "scope": "Cluster",
            "versions": [{
                "name": tpuquota.VERSION,
                "served": True,
                "storage": True,
                "schema": schema_doc,
                "subresources": {"status": {}},
                "additionalPrinterColumns": [
                    {"name": "Tenant", "type": "string",
                     "jsonPath": ".spec.tenant"},
                    {"name": "MaxSlices", "type": "integer",
                     "jsonPath": ".spec.maxSlices"},
                ],
            }],
        },
    }


# ------------------------------------------------------------------- manager

def parse_params_env(text: str) -> dict[str, str]:
    """THE params.env parser — shared with ci/release.py's stamping so the
    two can never drift on format (comments skipped, key=value only)."""
    out: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, sep, value = line.partition("=")
        if sep:
            out[key.strip()] = value.strip()
    return out


def format_params_env(params: dict[str, str]) -> str:
    return "".join(f"{key}={value}\n" for key, value in params.items())


def params_env_path(repo_root: Path | None = None) -> Path:
    root = repo_root or Path(__file__).resolve().parents[2]
    return root / "config/manager/params.env"


def _committed_image_pins() -> dict[str, str]:
    """Image references already pinned in the committed params.env (the
    release pipeline stamps digest-pinned refs there, ci/release.py). The
    generator preserves them so `make manifests` / the drift gate never
    silently un-pins a release — the reference's params.env works the same
    way: committed pins are the source of truth, updated by its
    image-updater workflows. A missing file is the bootstrap case (first
    generation into a fresh tree) — any other read error must surface."""
    path = params_env_path()
    if not path.exists():
        return {}
    return parse_params_env(path.read_text())


def params_env() -> str:
    """odh config/base/params.env analog: image + per-feature flags pinned in
    one file, piped into the Deployment by kustomize replacements. Image
    keys keep any committed (release-stamped) pin; everything else is
    generator-owned."""
    defaults = {
        MANAGER_IMAGE_PARAM: DEFAULT_MANAGER_IMAGE,
        "tpu-notebook-image":
            "us-docker.pkg.dev/kubeflow-tpu/jax-notebook:latest",
        "auth-proxy-image": "kube-rbac-proxy:latest",
        "notebook-gateway-name": "data-science-gateway",
        "notebook-gateway-namespace": "openshift-ingress",
    }
    image_keys = (MANAGER_IMAGE_PARAM, "tpu-notebook-image",
                  "auth-proxy-image")
    committed = _committed_image_pins()
    merged = {key: committed.get(key, default) if key in image_keys
              else default
              for key, default in defaults.items()}
    return "".join(f"{key}={value}\n" for key, value in merged.items())


def culler_configmap() -> dict:
    """Culler config ConfigMap (reference
    notebook-controller/config/manager/manager.yaml:44-57 wires
    ENABLE_CULLING/CULL_IDLE_TIME/IDLENESS_CHECK_PERIOD from
    notebook-controller-culler-config)."""
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": "notebook-controller-culler-config",
                     "namespace": NAMESPACE},
        "data": {
            "ENABLE_CULLING": "false",
            "CULL_IDLE_TIME": "1440",
            "IDLENESS_CHECK_PERIOD": "1",
        },
    }


CORE_DEPLOYMENT = "kubeflow-tpu-notebook-controller"
EXTENSION_DEPLOYMENT = "kubeflow-tpu-extension-controller"


def _manager_deployment(name: str, component: str, *,
                        webhook: bool, culler_env: bool) -> dict:
    """One manager Deployment; the reference ships TWO (notebook-controller
    and odh-notebook-controller config trees) cooperating only through
    apiserver state — ``--components`` selects the half."""
    env = [{"name": "K8S_NAMESPACE",
            "valueFrom": {"fieldRef": {"fieldPath": "metadata.namespace"}}}]
    if culler_env:
        env += [
            {"name": var,
             "valueFrom": {"configMapKeyRef": {
                 "name": "notebook-controller-culler-config", "key": var,
                 "optional": True}}}
            for var in ("ENABLE_CULLING", "CULL_IDLE_TIME",
                        "IDLENESS_CHECK_PERIOD")]
    # flags must exist in kubeflow_tpu/main.py argparse —
    # tests/test_manifests.py parses them against it.
    # --in-cluster: ServiceAccount-mount transport to the real apiserver
    # (cluster/http_client.py); without it the manager would reconcile an
    # empty in-process store and never touch the cluster
    args = ["--in-cluster", "--components", component, "--leader-elect",
            "--health-port", "8081"]
    ports = [{"containerPort": 8081, "name": "health", "protocol": "TCP"}]
    volume_mounts, volumes = [], []
    if webhook:
        args += ["--webhook-port", "8443", "--cert-dir",
                 "/etc/webhook/certs"]
        ports.insert(0, {"containerPort": 8443, "name": "webhook",
                         "protocol": "TCP"})
        # --cert-dir above: serving cert materialized by the cluster cert
        # machinery into this secret
        volume_mounts = [{"name": "webhook-certs",
                          "mountPath": "/etc/webhook/certs",
                          "readOnly": True}]
        volumes = [{"name": "webhook-certs",
                    "secret": {"secretName": "kubeflow-tpu-webhook-certs"}}]
    container = {
        "name": "manager",
        "image": DEFAULT_MANAGER_IMAGE,
        "command": ["python", "-m", "kubeflow_tpu.main"],
        "args": args,
        "env": env,
        "ports": ports,
        # reference manager probe shape (config/manager/manager.yaml:59-68)
        "livenessProbe": {
            "httpGet": {"path": "/healthz", "port": 8081},
            "initialDelaySeconds": 5, "periodSeconds": 10,
        },
        "readinessProbe": {
            "httpGet": {"path": "/readyz", "port": 8081},
            "initialDelaySeconds": 5, "periodSeconds": 10,
        },
        "resources": {
            "requests": {"cpu": "100m", "memory": "128Mi"},
            "limits": {"cpu": "500m", "memory": "512Mi"},
        },
    }
    if volume_mounts:
        container["volumeMounts"] = volume_mounts
    pod_spec = {"serviceAccountName": CORE_DEPLOYMENT,
                "containers": [container]}
    if volumes:
        pod_spec["volumes"] = volumes
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": NAMESPACE,
                     "labels": {"app": name}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": pod_spec,
            },
        },
    }


def manager_deployment() -> dict:
    """Core half: the notebook-controller binary (core reconciler + culler,
    no webhooks)."""
    return _manager_deployment(CORE_DEPLOYMENT, "core",
                               webhook=False, culler_env=True)


def extension_deployment() -> dict:
    """Platform half: the odh manager (extension reconciler + admission
    webhooks behind the webhook Service)."""
    return _manager_deployment(EXTENSION_DEPLOYMENT, "extension",
                               webhook=True, culler_env=False)


def _health_service(app: str) -> dict:
    return {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": app, "namespace": NAMESPACE,
                     "labels": {"app": app}},
        "spec": {
            "ports": [{"name": "health", "port": 8081,
                       "targetPort": 8081, "protocol": "TCP"}],
            "selector": {"app": app}},
    }


def manager_health_service() -> dict:
    """Core manager's health/metrics Service: Prometheus scrape target and
    the endpoint the pod-kill/outage chaos steady-state checks probe."""
    return _health_service(CORE_DEPLOYMENT)


def extension_health_service() -> dict:
    """Extension manager's health/metrics Service — its readyz carries the
    webhook-listener check (webhook-disrupt's steady-state probe) and its
    metrics cover the admission + extension-reconciler series."""
    return _health_service(EXTENSION_DEPLOYMENT)


# ---------------------------------------------------------------------- rbac

def rbac_objects() -> list[dict]:
    rules = [
        {"apiGroups": [api.GROUP], "resources": ["notebooks"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": [api.GROUP], "resources": ["notebooks/status"],
         "verbs": ["get", "update", "patch"]},
        {"apiGroups": ["apps"], "resources": ["statefulsets"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": [""], "resources": ["services", "serviceaccounts",
                                          "configmaps", "secrets", "pods",
                                          "events"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": ["networking.k8s.io"], "resources": ["networkpolicies"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": ["networking.istio.io"], "resources": ["virtualservices"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": ["gateway.networking.k8s.io"],
         "resources": ["httproutes", "referencegrants"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": ["rbac.authorization.k8s.io"],
         "resources": ["rolebindings", "clusterrolebindings"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": ["coordination.k8s.io"], "resources": ["leases"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
    ]
    return [
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {"name": "kubeflow-tpu-notebook-controller",
                      "namespace": NAMESPACE}},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRole",
         "metadata": {"name": "kubeflow-tpu-notebook-controller"},
         "rules": rules},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRoleBinding",
         "metadata": {"name": "kubeflow-tpu-notebook-controller"},
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "ClusterRole",
                     "name": "kubeflow-tpu-notebook-controller"},
         "subjects": [{"kind": "ServiceAccount",
                       "name": "kubeflow-tpu-notebook-controller",
                       "namespace": NAMESPACE}]},
    ]


# ------------------------------------------------------------------- webhook

def webhook_objects() -> list[dict]:
    """Webhook Service (serving-cert annotation, odh
    config/webhook/kustomization.yaml:6-7) + Mutating/Validating
    configurations with failurePolicy=Fail (admission is a hard gate,
    notebook_mutating_webhook.go:54)."""
    service = {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {
            "name": "kubeflow-tpu-webhook-service",
            "namespace": NAMESPACE,
            "annotations": {names.SERVING_CERT_SECRET_ANNOTATION:
                            "kubeflow-tpu-webhook-certs"}},
        "spec": {
            "ports": [{"port": 443, "targetPort": 8443,
                       "protocol": "TCP"}],
            # webhooks are served by the EXTENSION manager, as in the
            # reference (odh main.go:306-331)
            "selector": {"app": EXTENSION_DEPLOYMENT}},
    }
    rule = {
        "apiGroups": [api.GROUP], "apiVersions": ["v1"],
        "operations": ["CREATE", "UPDATE"], "resources": ["notebooks"]}
    client_cfg = lambda path: {  # noqa: E731
        "service": {"name": "kubeflow-tpu-webhook-service",
                    "namespace": NAMESPACE, "path": path, "port": 443}}
    mutating = {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "MutatingWebhookConfiguration",
        "metadata": {
            "name": "kubeflow-tpu-mutating-webhook",
            "annotations": {names.INJECT_CABUNDLE_ANNOTATION: "true"}},
        "webhooks": [{
            "name": f"notebooks.{api.GROUP}",
            "admissionReviewVersions": ["v1"],
            "sideEffects": "None",
            "failurePolicy": "Fail",
            "clientConfig": client_cfg("/mutate-notebook-v1"),
            "rules": [rule],
        }],
    }
    validating = {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {
            "name": "kubeflow-tpu-validating-webhook",
            "annotations": {names.INJECT_CABUNDLE_ANNOTATION: "true"}},
        "webhooks": [{
            "name": f"validating.notebooks.{api.GROUP}",
            "admissionReviewVersions": ["v1"],
            "sideEffects": "None",
            "failurePolicy": "Fail",
            "clientConfig": client_cfg("/validate-notebook-v1"),
            "rules": [rule],
        }],
    }
    return [service, mutating, validating]


# ----------------------------------------------------------------- kustomize

def _kustomization(resources: list[str], **extra) -> dict:
    out = {"apiVersion": "kustomize.config.k8s.io/v1beta1",
           "kind": "Kustomization", "resources": resources}
    out.update(extra)
    return out


def render_kustomize_tree() -> dict[str, object]:
    """Full config/ tree as {relative_path: yaml_dict_or_list_or_str}.
    Mirrors the reference layout: crd/manager/rbac/webhook bases, a default
    composition, and the three overlays (kubeflow / openshift / standalone,
    notebook-controller/config/overlays)."""
    tree: dict[str, object] = {
        "crd/bases/kubeflow.org_notebooks.yaml": notebook_crd(),
        "crd/bases/tpu.kubeflow.org_slicepools.yaml": slicepool_crd(),
        "crd/bases/tpu.kubeflow.org_tpuquotas.yaml": tpuquota_crd(),
        "crd/kustomization.yaml":
            _kustomization(["bases/kubeflow.org_notebooks.yaml",
                            "bases/tpu.kubeflow.org_slicepools.yaml",
                            "bases/tpu.kubeflow.org_tpuquotas.yaml"]),
        "manager/manager.yaml": [manager_deployment(),
                                 extension_deployment(), culler_configmap(),
                                 manager_health_service(),
                                 extension_health_service()],
        "manager/params.env": params_env(),
        "manager/kustomization.yaml": _kustomization(
            ["manager.yaml"],
            configMapGenerator=[{
                "name": "kubeflow-tpu-params",
                "envs": ["params.env"],
                "options": {"disableNameSuffixHash": True}}]),
        "rbac/rbac.yaml": rbac_objects(),
        "rbac/kustomization.yaml": _kustomization(["rbac.yaml"]),
        "webhook/webhook.yaml": webhook_objects(),
        "webhook/kustomization.yaml": _kustomization(["webhook.yaml"]),
        "default/kustomization.yaml": _kustomization(
            ["../crd", "../rbac", "../manager", "../webhook"],
            namespace=NAMESPACE,
            # pipe params.env values into the Deployment (the odh
            # config/base/kustomization.yaml replacements pattern) — without
            # this the params file would be dead config
            replacements=[{
                "source": {"kind": "ConfigMap",
                           "name": "kubeflow-tpu-params",
                           "fieldPath": f"data.{MANAGER_IMAGE_PARAM}"},
                "targets": [
                    {"select": {"kind": "Deployment", "name": name},
                     "fieldPaths": [
                         "spec.template.spec.containers.0.image"]}
                    for name in (CORE_DEPLOYMENT, EXTENSION_DEPLOYMENT)
                ],
            }]),
        # overlays — feature flags via env patches, as the reference does
        # with its openshift/kubeflow/standalone overlays
        "overlays/gke/kustomization.yaml": _kustomization(
            ["../../default"],
            patches=[{"patch": yaml.safe_dump([
                {"op": "add",
                 "path": "/spec/template/spec/containers/0/env/-",
                 "value": {"name": "ADD_FSGROUP", "value": "false"}},
            ], sort_keys=False),
                "target": {"kind": "Deployment",
                           "name": "kubeflow-tpu-notebook-controller"}}]),
        "overlays/culling/kustomization.yaml": _kustomization(
            ["../../default"],
            patches=[{"patch": yaml.safe_dump([
                {"op": "replace", "path": "/data/ENABLE_CULLING",
                 "value": "true"},
            ], sort_keys=False),
                "target": {"kind": "ConfigMap",
                           "name": "notebook-controller-culler-config"}}]),
        "overlays/standalone/kustomization.yaml": _kustomization(
            ["../../default"]),
        # istio overlay — the reference's kubeflow overlay turns on
        # VirtualService generation (USE_ISTIO, notebook_controller.go:558-658)
        "overlays/istio/kustomization.yaml": _kustomization(
            ["../../default"],
            patches=[{"patch": yaml.safe_dump([
                {"op": "add",
                 "path": "/spec/template/spec/containers/0/env/-",
                 "value": {"name": "USE_ISTIO", "value": "true"}},
                {"op": "add",
                 "path": "/spec/template/spec/containers/0/env/-",
                 "value": {"name": "ISTIO_GATEWAY",
                           "value": "kubeflow/kubeflow-gateway"}},
            ], sort_keys=False),
                "target": {"kind": "Deployment",
                           "name": "kubeflow-tpu-notebook-controller"}}]),
    }
    return tree


GENERATED_HEADER = ("# GENERATED by ci/generate_manifests.py — do not edit.\n"
                    "# Source of truth: kubeflow_tpu/deploy/manifests.py\n")


def _dump(content: object) -> str:
    if isinstance(content, str):
        return content
    buf = io.StringIO()
    docs = content if isinstance(content, list) else [content]
    yaml.safe_dump_all(docs, buf, sort_keys=False, default_flow_style=False)
    return GENERATED_HEADER + buf.getvalue()


def generate_all() -> dict[str, str]:
    return {path: _dump(content)
            for path, content in render_kustomize_tree().items()}


def write_tree(root: str | Path) -> list[Path]:
    root = Path(root)
    written = []
    for rel, text in generate_all().items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(text)
        written.append(path)
    return written
