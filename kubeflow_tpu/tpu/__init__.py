from .topology import SliceSpec, parse_slice_request, TpuRequestError

__all__ = ["SliceSpec", "parse_slice_request", "TpuRequestError"]
