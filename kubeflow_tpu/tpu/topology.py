"""TPU slice topology math.

The TPU-native core of the framework (SURVEY §7 stage 3): maps an accelerator
request expressed on the Notebook CR (annotations ``tpu.kubeflow.org/accelerator``
+ ``tpu.kubeflow.org/topology`` or shorthand like ``v5e-16``) to the concrete
provisioning facts the reconciler needs:

- ``num_workers``      → StatefulSet replicas (one pod per TPU VM / worker)
- ``chips_per_worker`` → ``google.com/tpu`` resource quantity per pod
- GKE nodeSelectors    → ``cloud.google.com/gke-tpu-accelerator`` and
                         ``cloud.google.com/gke-tpu-topology``
- worker env           → ``TPU_WORKER_ID`` (StatefulSet pod ordinal) and
                         ``TPU_WORKER_HOSTNAMES`` (headless-Service DNS)

The reference has no analog — its CRD passes the PodSpec through untouched
(components/notebook-controller/api/v1beta1/notebook_types.go:27-34) and its
GPU path is just a resource quantity. Topology-awareness is what makes
multi-host slices (one STS, N workers, slice-atomic lifecycle) possible.

Topology tables follow GKE's published TPU slice shapes: v4/v5p are 3-D tori
with 4 chips per VM; v5e/v6e are 2-D with single-host shapes up to 8 chips and
4 chips per VM in multi-host slices.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

from ..cluster.errors import InvalidError
from ..utils import names


class TpuRequestError(InvalidError):
    reason = "InvalidTPURequest"


@dataclass(frozen=True)
class Generation:
    name: str                  # "v4", "v5e", "v5p", "v6e"
    gke_accelerator: str       # nodeSelector value
    dims: int                  # topology dimensionality (2 or 3)
    chips_per_host: int        # chips per VM in multi-host slices
    max_single_host_chips: int # largest slice served by one worker VM
    max_chips: int             # largest supported slice


GENERATIONS: dict[str, Generation] = {
    "v4":  Generation("v4",  "tpu-v4-podslice",      3, 4, 4, 4096),
    "v5p": Generation("v5p", "tpu-v5p-slice",        3, 4, 4, 8960),
    "v5e": Generation("v5e", "tpu-v5-lite-podslice", 2, 4, 8, 256),
    "v6e": Generation("v6e", "tpu-v6e-slice",        2, 4, 8, 256),
}

# Canonical topology for a chip count (2-D generations). Mirrors GKE's
# supported v5e/v6e shapes.
_CHIPS_TO_TOPOLOGY_2D = {
    1: (1, 1), 4: (2, 2), 8: (2, 4), 16: (4, 4), 32: (4, 8),
    64: (8, 8), 128: (8, 16), 256: (16, 16),
}

_slice_short_re = re.compile(r"^(v[0-9]+[a-z]*)-([0-9]+)$")
_topology_re = re.compile(r"^[0-9]+(x[0-9]+){1,2}$")


@dataclass(frozen=True)
class SliceSpec:
    """Everything the provisioner needs to emit a slice-shaped StatefulSet."""
    generation: str            # "v5e"
    topology: tuple[int, ...]  # (4, 4)
    chips: int                 # 16
    num_workers: int           # 4  → STS replicas
    chips_per_worker: int      # 4  → google.com/tpu quantity
    gke_accelerator: str       # "tpu-v5-lite-podslice"

    @property
    def topology_str(self) -> str:
        return "x".join(str(d) for d in self.topology)

    @property
    def multi_host(self) -> bool:
        return self.num_workers > 1

    @property
    def short_name(self) -> str:
        return f"{self.generation}-{self.chips}"

    def node_selectors(self) -> dict[str, str]:
        return {
            names.GKE_TPU_ACCELERATOR_LABEL: self.gke_accelerator,
            names.GKE_TPU_TOPOLOGY_LABEL: self.topology_str,
        }

    def worker_hostnames(self, sts_name: str, headless_svc: str,
                         namespace: str) -> list[str]:
        """Stable DNS names of all workers through the headless Service —
        the value of TPU_WORKER_HOSTNAMES. Stability across pod restarts is
        guaranteed by StatefulSet ordinal naming + the headless Service
        (SURVEY §7 hard part 'TPU_WORKER_HOSTNAMES correctness')."""
        return [f"{sts_name}-{i}.{headless_svc}.{namespace}.svc"
                for i in range(self.num_workers)]


def _topology_for_chips(gen: Generation, chips: int) -> tuple[int, ...]:
    if gen.dims == 2:
        if chips not in _CHIPS_TO_TOPOLOGY_2D:
            raise TpuRequestError(
                f"{gen.name}-{chips}: unsupported chip count; supported: "
                f"{sorted(_CHIPS_TO_TOPOLOGY_2D)}")
        return _CHIPS_TO_TOPOLOGY_2D[chips]
    # 3-D: factor chips into the most cubic AxBxC with dims that are 1 or even
    if chips == 1:
        return (1, 1, 1)
    if chips % 4 != 0 or chips > gen.max_chips:
        raise TpuRequestError(
            f"{gen.name}-{chips}: 3-D slices must be a multiple of 4 chips "
            f"≤ {gen.max_chips}")
    c = round(chips ** (1 / 3))
    for a in range(c, 0, -1):
        if chips % a:
            continue
        rest = chips // a
        b = round(math.sqrt(rest))
        for bb in range(b, 0, -1):
            if rest % bb == 0 and bb >= a:
                return tuple(sorted((a, bb, rest // bb)))
    return (1, 1, chips)


def _spec_from(gen: Generation, topology: tuple[int, ...]) -> SliceSpec:
    chips = math.prod(topology)
    if chips > gen.max_chips:
        raise TpuRequestError(f"{gen.name} slice of {chips} chips exceeds max "
                              f"{gen.max_chips}")
    if chips <= gen.max_single_host_chips:
        num_workers, chips_per_worker = 1, chips
    else:
        if chips % gen.chips_per_host:
            raise TpuRequestError(
                f"{gen.name}-{chips}: multi-host slices must be a multiple of "
                f"{gen.chips_per_host} chips per worker")
        num_workers = chips // gen.chips_per_host
        chips_per_worker = gen.chips_per_host
    return SliceSpec(gen.name, topology, chips, num_workers, chips_per_worker,
                     gen.gke_accelerator)


def parse_topology(generation: str, topology: str) -> SliceSpec:
    gen = GENERATIONS.get(generation)
    if gen is None:
        raise TpuRequestError(
            f"unknown TPU generation {generation!r}; known: {sorted(GENERATIONS)}")
    if not _topology_re.match(topology):
        raise TpuRequestError(f"malformed topology {topology!r} (want e.g. 2x2 or 2x2x4)")
    dims = tuple(int(d) for d in topology.split("x"))
    if len(dims) != gen.dims:
        raise TpuRequestError(
            f"{gen.name} topologies are {gen.dims}-D; got {topology!r}")
    return _spec_from(gen, dims)


def parse_short_name(short: str) -> SliceSpec:
    """Parse shorthand like ``v5e-16`` (generation + total chips)."""
    m = _slice_short_re.match(short)
    if not m:
        raise TpuRequestError(f"malformed slice name {short!r} (want e.g. v5e-16)")
    generation, chips_s = m.group(1), m.group(2)
    gen = GENERATIONS.get(generation)
    if gen is None:
        raise TpuRequestError(
            f"unknown TPU generation {generation!r}; known: {sorted(GENERATIONS)}")
    chips = int(chips_s)
    return _spec_from(gen, _topology_for_chips(gen, chips))


def parse_slice_request(annotations: dict[str, str] | None) -> SliceSpec | None:
    """Extract a slice request from Notebook CR annotations. Returns None for
    CPU notebooks (no TPU annotations present).

    Accepted forms:
    - ``tpu.kubeflow.org/accelerator: v5e-16``            (shorthand)
    - ``tpu.kubeflow.org/accelerator: v5e`` +
      ``tpu.kubeflow.org/topology: 4x4``                  (explicit topology)
    """
    if not annotations:
        return None
    acc = annotations.get(names.TPU_ACCELERATOR_ANNOTATION)
    topo = annotations.get(names.TPU_TOPOLOGY_ANNOTATION)
    if acc is None and topo is None:
        return None
    if acc is None:
        raise TpuRequestError(
            f"{names.TPU_TOPOLOGY_ANNOTATION} requires "
            f"{names.TPU_ACCELERATOR_ANNOTATION}")
    if topo is not None:
        return parse_topology(acc, topo)
    if _slice_short_re.match(acc):
        return parse_short_name(acc)
    # bare generation without topology → smallest slice
    gen = GENERATIONS.get(acc)
    if gen is None:
        raise TpuRequestError(f"unknown TPU accelerator {acc!r}")
    return _spec_from(gen, (1,) * gen.dims)
