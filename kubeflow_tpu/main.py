"""Manager entrypoint — the analog of the reference's two main.go binaries.

Reference wiring being reproduced (notebook-controller/main.go:48-148 + odh
main.go:141-374):

- flag parsing (health-probe addr, webhook port/cert-dir, leader election,
  debug log) + env config (ControllerConfig.from_env);
- bootstrap TLS-profile fetch with hardened fallback, applied to the webhook
  listener; SecurityProfileWatcher triggers graceful shutdown on change so
  the process restarts with the new profile (odh main.go:178-234,344-367);
- manager cache with Secret/ConfigMap data stripped + live reads for those
  kinds (odh main.go:95-125,248-268) — our CachingClient;
- reconcilers + admission webhooks registered on one manager, healthz/readyz
  endpoints, optional leader election.

``build_manager`` is the composition root (importable, used by e2e tests —
the production path IS the tested path); ``main()`` adds flags/signals. The
client defaults to the in-process ClusterStore (the framework's apiserver);
a standalone run with ``--simulate-kubelet`` is a full working control plane
on one machine.

Run:  python -m kubeflow_tpu.main --simulate-kubelet --health-port 8081
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from .cluster.store import ClusterStore
from .controllers import setup_controllers
from .utils import tls_profile
from .utils.config import ControllerConfig
from .webhook.server import AdmissionServer

log = logging.getLogger("kubeflow_tpu.main")


def build_manager(store=None, config: ControllerConfig | None = None, *,
                  leader_elect: bool = False, health_port: int | None = None,
                  webhook_port: int | None = None,
                  cert_dir: str | None = None,
                  simulate_kubelet: bool = False,
                  components: str = "all",
                  max_concurrent_reconciles: int | None = None,
                  shards: int | None = None,
                  on_tls_change=None):
    """Compose the full production stack; returns (manager, shutdown_event).

    ``store`` is any object implementing the client protocol: the in-process
    ClusterStore (default), or an HttpApiClient pointed at a real apiserver —
    the reconcilers are identical either way (the reference's controllers are
    equally transport-agnostic behind controller-runtime's client,
    notebook-controller/main.go:95-148).

    ``components`` mirrors the reference's two manager binaries:
    ``core`` = notebook-controller (core reconciler + culler, no webhooks,
    own leader Lease), ``extension`` = the odh manager (extension
    reconciler + admission webhooks, its own Lease), ``all`` = both in one
    process (the standalone convenience). Split processes cooperate only
    through apiserver state, exactly like the reference pair (SURVEY §1).

    The returned manager's client is the read-cached view (Secret/ConfigMap
    payloads never cached); admission plugins and the optional HTTPS webhook
    server share one handler path. ``on_tls_change`` defaults to setting the
    shutdown event — the caller exits and the supervisor restarts the
    process with the new cluster TLS profile.
    """
    store = store if store is not None else ClusterStore()
    config = config or ControllerConfig.from_env()
    if shards is not None:
        # sharded multi-manager mode (--shards N): every replica must run
        # the same count — the namespace-hash shard map is computed
        # locally from it (SHARD_COUNT env is the manifest-friendly form)
        config.shard_count = shards
    shutdown = threading.Event()

    if components not in ("all", "core", "extension"):
        raise ValueError(f"unknown components selection: {components!r}")
    core = components in ("all", "core")
    extension = components in ("all", "extension")
    # setup_controllers owns the ONE read-cache layer (cached_reads):
    # wrapping here as well would stack two informer sets with duplicate
    # watch streams and snapshot LISTs
    mgr = setup_controllers(store, config, leader_elect=leader_elect,
                            health_port=health_port, core=core,
                            extension=extension, webhooks=extension,
                            max_concurrent_reconciles=max_concurrent_reconciles)
    client = mgr.client  # the cached view (Secret/CM/Event reads stay live)

    profile = tls_profile.fetch_apiserver_tls_profile(store)
    watcher = tls_profile.SecurityProfileWatcher(
        store, profile,
        on_change=on_tls_change or shutdown.set)
    watcher.setup()

    if webhook_port is not None and extension:
        # the webhook server belongs to the extension manager, as in the
        # reference (webhooks register on the odh binary, main.go:306-331)
        certfile = f"{cert_dir}/tls.crt" if cert_dir else None
        keyfile = f"{cert_dir}/tls.key" if cert_dir else None
        # same webhook objects the in-process admission plugins use — one
        # code path for cluster (HTTPS) and standalone (in-process) modes
        from .webhook import (NotebookMutatingWebhook,
                              NotebookValidatingWebhook)
        # admission reads/writes the LIVE store, never the manager cache:
        # mutating on a watch-fed view (e.g. resolving an ImageStream that
        # was updated milliseconds ago) would be a correctness hazard —
        # same invariant as the in-process admission plugins
        mgr.webhook_server = AdmissionServer(
            NotebookMutatingWebhook(store, config),
            NotebookValidatingWebhook(config),
            port=webhook_port, certfile=certfile, keyfile=keyfile,
            tls_profile=profile)
        if mgr.health_server is not None:
            mgr.health_server.add_readyz_check(
                "webhook", lambda: mgr.webhook_server.is_serving())

    if simulate_kubelet:
        from .cluster.kubelet import StatefulSetSimulator
        # reads through the manager's indexed informer cache when present:
        # pod lookups hit the 'statefulset' by-label index instead of
        # scanning the store's whole object map per reconcile
        StatefulSetSimulator(mgr.read_cache or store).setup(mgr)

    return mgr, shutdown


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--leader-elect", action="store_true",
                    help="enable Lease-based leader election")
    ap.add_argument("--health-port", type=int, default=8081,
                    help="healthz/readyz/metrics port (0 disables)")
    ap.add_argument("--webhook-port", type=int, default=8443)
    ap.add_argument("--cert-dir", default=None,
                    help="dir with tls.crt/tls.key for the webhook server "
                         "(absent → plain HTTP, dev only)")
    ap.add_argument("--simulate-kubelet", action="store_true",
                    help="run the StatefulSet/pod simulator (standalone)")
    ap.add_argument("--max-concurrent-reconciles", type=int, default=None,
                    metavar="N",
                    help="dispatch worker-pool size (controller-runtime "
                         "MaxConcurrentReconciles; default from "
                         "MAX_CONCURRENT_RECONCILES env, 4; 1 = the "
                         "classic single dispatch thread)")
    ap.add_argument("--shards", type=int, default=None, metavar="M",
                    help="shard reconcile ownership by namespace hash into "
                         "M shards (per-shard Lease election; run N "
                         "replicas with the SAME M against one apiserver "
                         "— each reconciles only its shards; SHARD_COUNT "
                         "env is equivalent, SHARD_IDENTITY pins the "
                         "replica identity)")
    ap.add_argument("--components", choices=("all", "core", "extension"),
                    default="all",
                    help="which manager to run: 'core' = the "
                         "notebook-controller binary (core reconciler + "
                         "culler), 'extension' = the odh manager "
                         "(extension reconciler + webhooks); the two "
                         "cooperate through apiserver state like the "
                         "reference's two Deployments")
    ap.add_argument("--debug-log", action="store_true")
    ap.add_argument("--log-format", choices=("text", "json"), default="text",
                    help="json = zap production-encoder analog (one JSON "
                         "object per line, RFC3339 ts)")
    # real-cluster transport: pick ONE of kubeconfig / api-server / in-cluster
    ap.add_argument("--kubeconfig", default=None,
                    help="reconcile a real cluster via this kubeconfig")
    ap.add_argument("--api-server", default=None,
                    help="reconcile a real cluster at this apiserver URL "
                         "(token via --api-token or SA mount)")
    ap.add_argument("--api-token", default=None)
    ap.add_argument("--in-cluster", action="store_true",
                    help="use the ServiceAccount mount (the deploy "
                         "manifests' mode)")
    ap.add_argument("--insecure-skip-tls-verify", action="store_true")
    ap.add_argument("--serve-apiserver", type=int, default=None,
                    metavar="PORT",
                    help="standalone mode: expose the in-process store over "
                         "HTTP so other processes share this cluster state")
    ap.add_argument("--apiserver-bind", default="127.0.0.1",
                    help="bind address for --serve-apiserver; non-loopback "
                         "requires --apiserver-token (the facade grants "
                         "full cluster read/write, Secrets included)")
    ap.add_argument("--audit-log", default=None, metavar="PATH",
                    help="with --serve-apiserver: append one NDJSON line "
                         "per mutating request (who changed what) — the "
                         "reference test suite's apiserver audit-log debug "
                         "hook")
    ap.add_argument("--apiserver-token", default=None,
                    help="bearer token required by --serve-apiserver "
                         "(env APISERVER_TOKEN also honored); TLS via "
                         "--cert-dir")
    ap.add_argument("--fault-plan", default=None, metavar="PATH",
                    help="with --serve-apiserver: arm the facade with a "
                         "wire-level FaultPlan (YAML: seed + rules of "
                         "429/503/reset/watch_kill/latency per verb/kind, "
                         "cluster/faults.py) — a standalone chaos "
                         "apiserver for exercising any manager's retry/"
                         "breaker behavior over real HTTP")
    ap.add_argument("--otlp-endpoint", default=None, metavar="URL",
                    help="export admission/controller spans as "
                         "OTLP/HTTP JSON to this collector base URL "
                         "(POSTs {URL}/v1/traces, like the reference's "
                         "OTel webhook instrumentation); absent → the "
                         "no-op provider")
    ap.add_argument("--trace-debug", action="store_true",
                    help="record reconcile traces in the in-process flight "
                         "recorder (last traces per notebook) and serve "
                         "them at /debug/notebooks/<ns>/<name>/trace on "
                         "the health port — no collector needed; combines "
                         "with --otlp-endpoint (recorder tees to OTLP)")
    return ap


def build_client_from_args(args):
    """Resolve the transport flags to a client, or None for the in-process
    store (client-go's loading order: explicit flag > kubeconfig > SA)."""
    from .cluster.http_client import HttpApiClient
    if args.api_server:
        return HttpApiClient(args.api_server, token=args.api_token,
                             verify=not args.insecure_skip_tls_verify)
    if args.kubeconfig:
        return HttpApiClient.from_kubeconfig(args.kubeconfig)
    if args.in_cluster:
        return HttpApiClient.in_cluster()
    return None


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    from .utils.logging import setup_logging
    setup_logging(debug=args.debug_log, fmt=args.log_format)

    otlp = None
    recorder = None
    if args.otlp_endpoint or args.trace_debug:
        from .utils import tracing
        if args.otlp_endpoint:
            otlp = tracing.OtlpHttpExporter(args.otlp_endpoint)
        exporter = otlp
        if args.trace_debug:
            # flight recorder in front; tees every span to OTLP when both
            # are requested
            recorder = tracing.FlightRecorder(inner=otlp)
            exporter = recorder
        tracing.set_provider(tracing.SDKProvider(exporter))
        log.info("tracing: otlp=%s flight_recorder=%s",
                 args.otlp_endpoint or "off",
                 "on" if recorder is not None else "off")

    client = build_client_from_args(args)
    mgr, shutdown = build_manager(
        store=client,
        leader_elect=args.leader_elect,
        health_port=args.health_port or None,
        webhook_port=args.webhook_port or None,
        cert_dir=args.cert_dir,
        components=args.components,
        max_concurrent_reconciles=args.max_concurrent_reconciles,
        shards=args.shards,
        simulate_kubelet=args.simulate_kubelet and client is None)

    if recorder is not None and mgr.health_server is not None:
        # the cli.py `trace` subcommand reads this endpoint
        mgr.health_server.flight_recorder = recorder

    apiserver = None
    if args.serve_apiserver is not None:
        if client is not None:
            log.error("--serve-apiserver requires the in-process store")
            return 2
        import os
        token = args.apiserver_token or os.environ.get("APISERVER_TOKEN")
        if args.apiserver_bind not in ("127.0.0.1", "localhost", "::1") \
                and not token:
            log.error("refusing to serve the apiserver facade on %s without "
                      "--apiserver-token: it grants full cluster read/write "
                      "(Secrets included) to any network peer",
                      args.apiserver_bind)
            return 2
        from .cluster.apiserver import ApiServerProxy
        fault_plan = None
        if args.fault_plan:
            from .cluster.faults import FaultPlan
            fault_plan = FaultPlan.from_file(args.fault_plan)
            log.warning("apiserver facade armed with fault plan %s "
                        "(%d rules) — injected 429/5xx/resets ahead",
                        args.fault_plan, len(fault_plan.rules))
        apiserver = ApiServerProxy(
            mgr.client.store, port=args.serve_apiserver,
            host=args.apiserver_bind, token=token,
            certfile=f"{args.cert_dir}/tls.crt" if args.cert_dir else None,
            keyfile=f"{args.cert_dir}/tls.key" if args.cert_dir else None,
            audit_log=args.audit_log,
            fault_plan=fault_plan)
        apiserver.start()
        log.info("apiserver facade listening on %s (auth=%s)",
                 apiserver.url, "token" if token else "none/loopback")

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: shutdown.set())
    if getattr(mgr, "webhook_server", None) is not None:
        mgr.webhook_server.start()
    mgr.start()
    log.info("manager started (leader_elect=%s)", args.leader_elect)
    shutdown.wait()
    log.info("shutting down")
    if apiserver is not None:
        apiserver.stop()
    if getattr(mgr, "webhook_server", None) is not None:
        mgr.webhook_server.stop()
    # stop the manager BEFORE closing its transport: the graceful
    # shutdown path writes (lease releases — leader and shard) and a
    # closed client would turn every one into a transport error, leaving
    # peers to wait out lease staleness instead of adopting immediately
    mgr.stop()
    if client is not None:
        client.close()
    if otlp is not None:
        otlp.shutdown()  # final span flush to the collector
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
