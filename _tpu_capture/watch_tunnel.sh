#!/bin/bash
# Round-4 tunnel watcher: probe the axon TPU backend until it answers, then
# exit 0 so the invoking session is re-triggered to run the live capture
# (bench.py all 8 ARCHIVE_METRICS + ci/tpu_numerics.py + ci/tpu_ctx_sweep.py).
# Probe = one time-boxed `jax.devices()` subprocess (the tunnel wedges at
# backend init when down; jax.devices() hangs forever in-process).
cd /root/repo
LOG=_tpu_capture/probe_log.txt
DEADLINE=$(( $(date +%s) + ${WATCH_DEADLINE_S:-39600} ))  # default 11h
N=$(grep -c '^....-' "$LOG" 2>/dev/null || echo 0)
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  N=$((N+1))
  OUT=$(timeout 90 python -c "import jax; d=jax.devices(); print(jax.default_backend(), len(d), getattr(d[0],'device_kind','?'))" 2>/dev/null | tail -1)
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  case "$OUT" in
    *tpu*|*TPU*|*axon*)
      echo "$TS probe $N: TUNNEL UP: $OUT" >> "$LOG"
      exit 0 ;;
    *)
      echo "$TS probe $N: tunnel down" >> "$LOG" ;;
  esac
  sleep 420
done
echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) watcher deadline reached, tunnel never returned" >> "$LOG"
exit 1
