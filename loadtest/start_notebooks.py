#!/usr/bin/env python3
"""Notebook fan-out load test.

Reference: notebook-controller/loadtest/start_notebooks.py:1-99 templates N
Notebook CRs (+ PVC each) and applies them with kubectl, as a manual
scalability probe. Two modes here:

- default (self-contained): drive the in-process control plane — apiserver,
  webhooks, both reconcilers, StatefulSet simulator — with N TPU notebooks
  and report creation→SliceReady latency percentiles and reconcile
  throughput. This is the control-plane scalability measurement the
  reference's script only eyeballs via kubectl.
- ``--emit-yaml``: print N templated Notebook CRs (with PVCs, like the
  reference's jupyter_test.yaml shape) for kubectl-apply against a real
  cluster.

Usage:
    python loadtest/start_notebooks.py --count 200
    python loadtest/start_notebooks.py --count 10 --emit-yaml | kubectl apply -f -
"""

from __future__ import annotations

import argparse
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def notebook_yaml(i: int, namespace: str, accelerator: str) -> str:
    return f"""---
apiVersion: v1
kind: PersistentVolumeClaim
metadata:
  name: loadtest-nb-{i}-pvc
  namespace: {namespace}
spec:
  accessModes: [ReadWriteOnce]
  resources:
    requests:
      storage: 10Gi
---
apiVersion: kubeflow.org/v1
kind: Notebook
metadata:
  name: loadtest-nb-{i}
  namespace: {namespace}
  annotations:
    tpu.kubeflow.org/accelerator: "{accelerator}"
spec:
  template:
    spec:
      containers:
      - name: loadtest-nb-{i}
        image: jupyter-minimal:latest
        volumeMounts:
        - name: workspace
          mountPath: /home/jovyan
      volumes:
      - name: workspace
        persistentVolumeClaim:
          claimName: loadtest-nb-{i}-pvc
"""


def run_inprocess(count: int, namespace: str, accelerator: str,
                  timeout: float, server: str | None = None,
                  workers: int = 4) -> int:
    """Default: drive the in-process control plane. With ``server``: the
    same fan-out over REAL HTTP against a running apiserver (start one with
    ``python -m kubeflow_tpu.main --serve-apiserver PORT --simulate-kubelet``)
    — transport latency included in every number."""
    from kubeflow_tpu.api import types as api
    from kubeflow_tpu.utils import names

    mgr = None
    if server:
        from kubeflow_tpu.cluster.http_client import HttpApiClient
        store = HttpApiClient(server)
    else:
        from kubeflow_tpu.cluster.kubelet import StatefulSetSimulator
        from kubeflow_tpu.cluster.store import ClusterStore
        from kubeflow_tpu.controllers import setup_controllers

        store = ClusterStore()
        mgr = setup_controllers(store, max_concurrent_reconciles=workers)
        # indexed reads for the simulator too (shares the manager cache)
        StatefulSetSimulator(mgr.read_cache or store,
                             boot_delay_s=0.0).setup(mgr)
        mgr.start()
    created: dict[str, float] = {}
    ready: dict[str, float] = {}
    t0 = time.monotonic()
    for i in range(count):
        name = f"loadtest-nb-{i}"
        store.create(api.new_notebook(
            name, namespace,
            annotations={names.TPU_ACCELERATOR_ANNOTATION: accelerator}))
        created[name] = time.monotonic()
    deadline = time.monotonic() + timeout
    while len(ready) < count and time.monotonic() < deadline:
        for name in list(created):
            if name in ready:
                continue
            nb = store.get_or_none(api.KIND, namespace, name)
            cond = api.get_condition(nb, api.CONDITION_SLICE_READY) \
                if nb else None
            if cond and cond["status"] == "True":
                ready[name] = time.monotonic() - created[name]
        time.sleep(0.01)
    total = time.monotonic() - t0
    if mgr is not None:
        mgr.stop()
    if len(ready) < count:
        print(f"FAIL: only {len(ready)}/{count} notebooks became SliceReady "
              f"within {timeout}s")
        return 1
    print(f"notebooks: {count}  workers: {workers}  wall: {total:.2f}s  "
          f"throughput: {count/total:.1f} nb/s")
    _print_latencies(sorted(ready.values()))
    return 0


def _print_latencies(lat: list[float]) -> None:
    """The shared create→SliceReady percentile line (both modes)."""
    if not lat:
        return
    print(f"create→SliceReady  p50: {statistics.median(lat)*1000:.1f}ms  "
          f"p95: {lat[int(0.95*(len(lat)-1))]*1000:.1f}ms  "
          f"max: {lat[-1]*1000:.1f}ms")


def run_wire(count: int, namespace: str, accelerator: str, timeout: float,
             max_requests_per_nb: float | None = None,
             workers: int = 4, apiserver_latency_ms: float = 0.0,
             fault_rate: float = 0.0, fault_plan: str | None = None,
             fault_seed: int | None = 7,
             list_page_size: int | None = None,
             max_full_scans: int | None = None,
             preempt_rate: float = 0.0,
             watch_kill_after_s: float = 0.0,
             max_relist_resyncs: int | None = None,
             min_conn_reuse: float | None = None,
             settle_s: float = 0.0,
             pool_warm: int = 0,
             boot_delay_ms: float = 0.0,
             stats_out: dict | None = None) -> int:
    """Controller wire-cost measurement: the full controller stack runs
    over a real HTTP apiserver while the load generator drives the store
    directly, so ``rest_client_requests_total`` counts ONLY controller
    traffic. Reports apiserver requests per notebook — the number the
    reference's informer-cache architecture keeps small, and the regression
    guard for full-LIST/GET-storm patterns on the hot paths (metrics
    scrape, Event predicate).

    ``fault_rate`` arms the apiserver with the standard mixed wire-fault
    plan (429-with-Retry-After / 503 / connection reset per verb +
    watch-stream kills, cluster/faults.FaultPlan.uniform) at that
    per-request rate; ``fault_plan`` loads a custom plan YAML instead.
    With faults on, the run keeps an audit tap and fails on any duplicate
    side-effect write (a retried create applying twice) in addition to
    the convergence bound — the chaos soak contract.

    ``list_page_size`` pages every controller LIST through
    ``limit``/``continue`` chunks of that size (exercises pagination on
    the wire); ``max_full_scans`` bounds ``cache_full_scans_total`` — 0
    asserts the reconcile hot path never walks a whole cache kind.

    ``preempt_rate`` preempts the node under worker 0 of that fraction of
    the fleet mid-fan-out (each target's node is killed the moment its
    slice first reaches SliceReady — the worst time). The run then also
    fails on: any StatefulSet ever OBSERVED at a partial replica count
    (slice atomicity — replicas must only ever be 0 or the full worker
    count), any preempted slice not repaired back to SliceReady with its
    health state cleared, and any slice quarantined by a single
    preemption.

    ``watch_kill_after_s`` arms a watch-kill-only FaultPlan: EVERY watch
    stream is killed that long after connecting, for the whole run — the
    RV-resume chaos shape. ``max_relist_resyncs`` bounds
    ``watch_resumes_total{mode="relist"}`` (0 = every reconnect resumed
    from the server watch cache, zero full re-LISTs);
    ``min_conn_reuse`` bounds requests-per-connection from below (the
    keep-alive pool's proof that connections don't scale with requests).
    ``settle_s`` keeps the run alive that long after convergence so
    reconnect chaos actually happens on an idle fleet too.

    ``pool_warm`` pre-creates a SlicePool with that warm-slice target and
    waits for it to warm BEFORE the fan-out, so every notebook takes the
    bind path (controllers/slicepool.py); with pool_warm >= count the run
    fails on any bind miss (a notebook that cold-rolled). ``boot_delay_ms``
    is the simulated per-pod provisioning cost (node spin-up + image pull)
    — the cost a warm bind exists to not pay. ``stats_out`` (a dict)
    receives wall/p50/req-per-notebook for phase-vs-phase comparisons."""
    import tempfile

    from kubeflow_tpu.api import types as api
    from kubeflow_tpu.cluster.apiserver import ApiServerProxy
    from kubeflow_tpu.cluster.experiments import audit_duplicate_creates
    from kubeflow_tpu.cluster.faults import FaultPlan
    from kubeflow_tpu.cluster.http_client import HttpApiClient
    from kubeflow_tpu.cluster.kubelet import StatefulSetSimulator
    from kubeflow_tpu.cluster.store import ClusterStore
    from kubeflow_tpu.controllers import Manager, setup_controllers
    from kubeflow_tpu.utils import names
    from kubeflow_tpu.utils.metrics import MetricsRegistry

    plan = None
    audit_needed = False
    if fault_plan:
        plan = FaultPlan.from_file(fault_plan)
        audit_needed = True
    elif fault_rate > 0:
        plan = FaultPlan.uniform(fault_rate, seed=fault_seed)
        audit_needed = True
    elif watch_kill_after_s > 0:
        # watch-kill-only chaos: streams die, mutations never do — no
        # duplicate-write ambiguity to audit
        from kubeflow_tpu.cluster.faults import FAULT_WATCH_KILL, FaultRule
        plan = FaultPlan([FaultRule(FAULT_WATCH_KILL, 1.0,
                                    after_s=watch_kill_after_s)],
                         seed=fault_seed)
    audit_path = None
    if audit_needed:
        audit_file = tempfile.NamedTemporaryFile(suffix=".ndjson",
                                                 delete=False)
        audit_file.close()
        audit_path = audit_file.name

    from kubeflow_tpu.api.slicepool import install_slicepool_crd

    store = ClusterStore()
    api.install_notebook_crd(store)
    install_slicepool_crd(store)
    cleanups = []
    try:
        # the simulator reads through its own indexed informer cache (the
        # real STS controller's shape): pod lookups hit the 'statefulset'
        # by-label index instead of scanning the store's whole object map
        # per reconcile — at 2000 notebooks that scan is ~10k objects twice
        # per reconcile and dominates the cluster-side wall
        from kubeflow_tpu.cluster.cache import CachingClient
        sim_cache = CachingClient(store, auto_informer=False,
                                  disable_for=())
        sim_mgr = Manager(sim_cache, read_cache=sim_cache)
        StatefulSetSimulator(sim_cache,
                             boot_delay_s=boot_delay_ms / 1000.0
                             ).setup(sim_mgr)
        sim_mgr.start()
        cleanups.append(sim_mgr.stop)
        proxy = ApiServerProxy(store,
                               latency_s=apiserver_latency_ms / 1000.0,
                               fault_plan=plan, audit_log=audit_path)
        proxy.start()
        cleanups.append(proxy.stop)
        client = HttpApiClient(proxy.url, list_page_size=list_page_size)
        cleanups.append(client.close)
        metrics = MetricsRegistry()
        # one exposition for the whole watch path: the proxy registers the
        # serve-side coalescing counter and passes the registry down to
        # the store (watch-cache evictions)
        proxy.attach_metrics(metrics)
        mgr = setup_controllers(client, metrics=metrics,
                                max_concurrent_reconciles=workers)
        mgr.start()
        cleanups.append(mgr.stop)
        requests = metrics.counter("rest_client_requests_total", "")
        # let the watch backfills settle so the baseline excludes boot cost
        time.sleep(0.3)
        if pool_warm > 0:
            # warm the pool BEFORE the fan-out (and before the request
            # baseline: warm-up is capacity provisioning, not per-notebook
            # bind cost — exactly the cost split the pool exists for)
            from kubeflow_tpu.api.slicepool import new_slice_pool
            from kubeflow_tpu.utils.k8s import get_annotation
            store.create(new_slice_pool("loadtest-pool", accelerator,
                                        pool_warm))
            warm_deadline = time.monotonic() + timeout

            def _warm_count() -> int:
                return sum(
                    1 for s in store.list("StatefulSet", "tpu-slice-pools")
                    if get_annotation(s, names.POOL_STATE_ANNOTATION)
                    == names.POOL_STATE_WARM)
            while time.monotonic() < warm_deadline:
                if _warm_count() >= pool_warm:
                    break
                time.sleep(0.05)
            else:
                print(f"FAIL: pool never reached {pool_warm} warm slices "
                      f"(have {_warm_count()})")
                return 1
        baseline = requests.total()
        # per-notebook create→SliceReady latency, observed via a store
        # watch — a tight full-LIST poll at a 500-notebook fan-out costs
        # ~17 ms/scan of deep copies and perturbs the very system under
        # measurement (it pins a core against the controllers' GIL time)
        import math
        import threading

        from kubeflow_tpu.cluster.kubelet import kill_node
        from kubeflow_tpu.tpu import topology
        ready_at: dict[str, float] = {}
        all_ready = threading.Event()
        # slice-atomicity observer: EVERY StatefulSet write the apiserver
        # fans out must show replicas at 0 or the full worker count —
        # a partial value here is a broken repair/scale path, no matter
        # how briefly it existed
        full_workers = topology.parse_short_name(accelerator).num_workers
        partial_observed: list[tuple[str, object]] = []

        def on_sts_event(ev):
            if ev.type == "DELETED":
                return
            replicas = (ev.obj.get("spec") or {}).get("replicas")
            if replicas not in (0, full_workers):
                partial_observed.append(
                    (ev.obj["metadata"]["name"], replicas))
        store.watch("StatefulSet", on_sts_event, namespace=namespace)

        # node-preemption injection: the first ceil(count*rate) notebooks
        # lose the node under worker 0 the moment their slice first turns
        # Ready — mid-fan-out, while the controllers are busiest
        preempt_targets = {f"loadtest-nb-{i}"
                           for i in range(math.ceil(count * preempt_rate))} \
            if preempt_rate > 0 else set()
        preempted: set[str] = set()

        def _preempt(name: str) -> None:
            for pod in store.list("Pod", namespace,
                                  {names.NOTEBOOK_NAME_LABEL: name}):
                if pod.get("metadata", {}).get("labels", {}).get(
                        "apps.kubernetes.io/pod-index") == "0":
                    node = (pod.get("spec") or {}).get("nodeName")
                    if node:
                        kill_node(store, node)
                        preempted.add(name)
                    return

        def on_event(ev):
            nb = ev.obj
            name = nb["metadata"]["name"]
            if name not in ready_at and \
                    (api.get_condition(nb, api.CONDITION_SLICE_READY)
                     or {}).get("status") == "True":
                ready_at[name] = time.monotonic()
                if name in preempt_targets and name not in preempted:
                    _preempt(name)
                if len(ready_at) >= count:
                    all_ready.set()
        store.watch(api.KIND, on_event, namespace=namespace)

        if count <= 0:
            print("notebooks: 0 — nothing to do")
            return 0
        t0 = time.monotonic()
        created_at = {}
        for i in range(count):
            name = f"loadtest-nb-{i}"
            created_at[name] = time.monotonic()
            store.create(api.new_notebook(
                name, namespace,
                annotations={names.TPU_ACCELERATOR_ANNOTATION: accelerator}))
        all_ready.wait(timeout)
        # bind-path request cost snapshot AT convergence: pool re-warming
        # continues in the background (replacement capacity, not
        # per-notebook cost) and must not pollute the comparison
        converged_requests = requests.total()
        if settle_s > 0:
            # idle-fleet window: watch chaos keeps firing while nothing
            # changes — reconnects must resume off bookmarks, not relist
            time.sleep(settle_s)
        # preempted slices must come back: repaired slice-atomically to
        # SliceReady with the health state cleared and NO quarantine (a
        # single preemption is normal fleet weather, not a poison pill)
        stuck_repairs: list[str] = []
        quarantined: list[str] = []
        if preempted:
            deadline = t0 + timeout

            def _unrepaired() -> list[str]:
                out = []
                for name in sorted(preempted):
                    nb = store.get_or_none(api.KIND, namespace, name)
                    if nb is None:
                        out.append(name)
                        continue
                    anns = nb.get("metadata", {}).get("annotations", {}) or {}
                    cond = (api.get_condition(nb, api.CONDITION_SLICE_READY)
                            or {})
                    if cond.get("status") != "True" or \
                            anns.get(names.SLICE_HEALTH_ANNOTATION):
                        out.append(name)
                return out

            while time.monotonic() < deadline:
                stuck_repairs = _unrepaired()
                if not stuck_repairs:
                    break
                time.sleep(0.05)
            else:
                stuck_repairs = _unrepaired()
            for name in sorted(preempted):
                nb = store.get_or_none(api.KIND, namespace, name)
                anns = (nb or {}).get("metadata", {}).get("annotations",
                                                          {}) or {}
                if anns.get(names.QUARANTINE_ANNOTATION):
                    quarantined.append(name)
        store.unwatch(on_event)
        store.unwatch(on_sts_event)
        ready = len(ready_at)
        wall = time.monotonic() - t0
        # one metrics scrape, so the notebook_running LIST cost is included
        metrics.expose()
        per_nb = (requests.total() - baseline) / max(count, 1)
        latencies = sorted(ready_at[n] - created_at[n] for n in ready_at)
        if stats_out is not None:
            stats_out.update({
                "wall_s": wall,
                "p50_s": statistics.median(latencies) if latencies else None,
                "req_per_nb": (converged_requests - baseline)
                / max(count, 1),
            })
        if ready < count:
            stuck = [n for n in created_at if n not in ready_at]
            print(f"FAIL: only {ready}/{count} notebooks became SliceReady "
                  f"within {timeout}s (stuck: {stuck[:5]}"
                  f"{'...' if len(stuck) > 5 else ''})")
            return 1
        faults_note = ""
        if plan is not None:
            injected = plan.injected()
            faults_note = (f"  injected faults: {plan.injected_total()} "
                           f"({dict(sorted(injected.items()))})")
        if preempted:
            repairs = metrics.counter("slice_repairs_total", "").total()
            faults_note += (f"  preempted nodes: {len(preempted)}  "
                            f"slice repairs: {repairs:.0f}")
        full_scans = metrics.counter("cache_full_scans_total", "").total()
        index_lookups = metrics.counter("cache_index_lookups_total",
                                        "").total()
        read_s = metrics.histogram("reconcile_read_seconds", "")
        write_s = metrics.histogram("reconcile_write_seconds", "")
        print(f"notebooks: {count}  workers: {workers}  wall: {wall:.2f}s  "
              f"controller apiserver requests/notebook: {per_nb:.1f}"
              f"{faults_note}")
        print(f"cache: {index_lookups:.0f} index lookups, "
              f"{full_scans:.0f} full scans  "
              f"phase wall: read {read_s.total_sum():.2f}s / "
              f"write {write_s.total_sum():.2f}s over "
              f"{read_s.total_count():.0f} reconciles")
        resumes_metric = metrics.counter("watch_resumes_total", "")
        resumed = resumes_metric.sum_where({"mode": "resume"})
        relisted = resumes_metric.sum_where({"mode": "relist"})
        evictions = metrics.counter("watch_cache_evictions_total",
                                    "").total()
        coalesced = metrics.counter("watch_queue_coalesced_total",
                                    "").total()
        conns_metric = metrics.counter("rest_client_connections_opened_total",
                                       "")
        pooled_conns = conns_metric.sum_where({"type": "pooled"})
        stream_conns = conns_metric.sum_where({"type": "stream"})
        reqs_total = requests.total()
        # reuse = request-path requests per pooled connection. Watch
        # connect GETs each ride a dedicated stream connection (one
        # stream = one connection by design; chaos churns those
        # legitimately) — subtract them from the numerator or every
        # stream would inflate the pooled ratio by ~1 request with no
        # pooled connection in the denominator
        pooled_reqs = max(reqs_total - stream_conns, 0.0)
        reuse = pooled_reqs / pooled_conns if pooled_conns else 0.0
        print(f"watch: {resumed:.0f} RV-resumes, {relisted:.0f} relist "
              f"resyncs, {evictions:.0f} cache evictions, "
              f"{coalesced:.0f} coalesced frames  "
              f"transport: {pooled_conns:.0f} pooled + {stream_conns:.0f} "
              f"stream connections for {reqs_total:.0f} requests "
              f"(reuse {reuse:.1f}x)")
        _print_latencies(sorted(ready_at[n] - created_at[n]
                                for n in ready_at))
        if max_requests_per_nb is not None and per_nb > max_requests_per_nb:
            print(f"FAIL: {per_nb:.1f} requests/notebook exceeds bound "
                  f"{max_requests_per_nb}")
            return 1
        if max_full_scans is not None and full_scans > max_full_scans:
            print(f"FAIL: {full_scans:.0f} cache full scans exceed bound "
                  f"{max_full_scans} (an unindexed hot-path LIST crept in)")
            return 1
        if max_relist_resyncs is not None:
            if watch_kill_after_s > 0 and resumed == 0:
                # vacuous-pass guard: the kill plan must actually have
                # forced reconnects for the zero-relist bound to mean
                # anything
                print("FAIL: watch-kill chaos armed but no RV-resume ever "
                      "happened (streams never reconnected?)")
                return 1
            if relisted > max_relist_resyncs:
                print(f"FAIL: {relisted:.0f} relist resyncs exceed bound "
                      f"{max_relist_resyncs} (a reconnect fell off the "
                      f"resume path)")
                return 1
        if min_conn_reuse is not None and reuse < min_conn_reuse:
            print(f"FAIL: connection reuse {reuse:.1f}x below bound "
                  f"{min_conn_reuse}x ({pooled_conns:.0f} pooled "
                  f"connections for {pooled_reqs:.0f} pooled-path requests "
                  f"— keep-alive pooling regressed)")
            return 1
        if pool_warm > 0:
            from kubeflow_tpu.utils.k8s import get_annotation
            bound, missed = [], []
            for name in created_at:
                nb = store.get_or_none(api.KIND, namespace, name)
                if nb is None:
                    continue
                if get_annotation(nb, names.BOUND_SLICE_ANNOTATION):
                    bound.append(name)
                elif get_annotation(nb, names.POOL_BIND_MISS_ANNOTATION):
                    missed.append(name)
            print(f"pool: {len(bound)}/{count} warm-bound, "
                  f"{len(missed)} bind misses")
            if pool_warm >= count and missed:
                print(f"FAIL: pool had capacity for the whole fleet but "
                      f"{len(missed)} notebook(s) missed the bind path: "
                      f"{missed[:5]}")
                return 1
        if partial_observed:
            sample = partial_observed[:5]
            print(f"FAIL: {len(partial_observed)} partial-slice replica "
                  f"states observed (must only ever be 0 or "
                  f"{full_workers}): {sample}")
            return 1
        if stuck_repairs:
            print(f"FAIL: {len(stuck_repairs)} preempted notebook(s) not "
                  f"repaired back to SliceReady: {stuck_repairs[:5]}")
            return 1
        if quarantined:
            print(f"FAIL: single preemption quarantined {quarantined[:5]} "
                  f"(poison pill must need repeated FAILED repairs)")
            return 1
        if preempt_rate > 0 and not preempted:
            # vacuous-pass guard: a broken pod→node binding (or a drifted
            # worker-0 lookup) must fail the run, not silently skip every
            # repair assertion below
            print("FAIL: --preempt-rate set but no node was ever preempted "
                  "(worker-0 pods had no node binding?)")
            return 1
        if preempted:
            repairs = metrics.counter("slice_repairs_total", "").total()
            if repairs < len(preempted):
                # recovery without enough slice rolls means some slice
                # self-healed pod-by-pod — Ready pods, broken JAX mesh
                print(f"FAIL: {len(preempted)} preemptions but only "
                      f"{repairs:.0f} slice-atomic repairs (a worker was "
                      f"replaced without re-forming the mesh)")
                return 1
        if audit_path is not None:
            duplicates = audit_duplicate_creates(audit_path)
            if duplicates:
                print("FAIL: duplicate side-effect writes under faults:")
                for dup in duplicates:
                    print(f"  {dup}")
                return 1
            print("audit: no duplicate side-effect writes")
        return 0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(f"loadtest: cleanup failed: {e}\n")
        if audit_path is not None:
            try:
                Path(audit_path).unlink()
            except OSError:
                pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--count", type=int, default=50)
    ap.add_argument("--namespace", default="loadtest")
    ap.add_argument("--accelerator", default="v5e-4")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--emit-yaml", action="store_true",
                    help="print CRs for kubectl instead of running in-process")
    ap.add_argument("--server", default=None,
                    help="drive a running apiserver over HTTP instead of "
                         "the in-process stack (URL)")
    ap.add_argument("--wire", action="store_true",
                    help="run the controllers over a local HTTP apiserver "
                         "and report apiserver requests per notebook")
    ap.add_argument("--max-requests-per-nb", type=float, default=None,
                    help="with --wire: fail if controller apiserver "
                         "requests per notebook exceed this bound")
    ap.add_argument("--workers", type=int, default=4,
                    help="manager MaxConcurrentReconciles (dispatch "
                         "worker-pool size; 1 = single-thread baseline)")
    ap.add_argument("--apiserver-latency-ms", type=float, default=0.0,
                    help="with --wire: inject this request round-trip "
                         "latency at the apiserver (a localhost facade "
                         "has ~0 RTT; production apiservers have 1-10 ms "
                         "— the regime concurrent dispatch exists for)")
    ap.add_argument("--fault-rate", type=float, default=0.0,
                    help="with --wire: per-request probability of an "
                         "injected wire fault (429/503/reset/watch-kill "
                         "mix, cluster/faults.FaultPlan.uniform); the run "
                         "also fails on any duplicate side-effect write")
    ap.add_argument("--fault-plan", default=None, metavar="PATH",
                    help="with --wire: load a custom FaultPlan YAML "
                         "instead of the uniform mix")
    ap.add_argument("--fault-seed", type=int, default=7,
                    help="seed for the injected-fault RNG (replayable runs)")
    ap.add_argument("--list-page-size", type=int, default=None,
                    help="with --wire: page every controller LIST through "
                         "limit/continue chunks of this size (exercises "
                         "apiserver pagination on the wire; bounds resync "
                         "memory on big fleets)")
    ap.add_argument("--max-full-scans", type=int, default=None,
                    help="with --wire: fail if cache_full_scans_total "
                         "exceeds this (0 = assert the reconcile hot path "
                         "never walks a whole cache kind)")
    ap.add_argument("--preempt-rate", type=float, default=0.0,
                    help="with --wire: preempt the node under worker 0 of "
                         "this fraction of the fleet as each slice first "
                         "turns Ready; the run fails on any partially "
                         "scaled StatefulSet, unrepaired slice, or "
                         "quarantine from a single preemption")
    ap.add_argument("--watch-kill-after-s", type=float, default=0.0,
                    help="with --wire: kill EVERY watch stream this long "
                         "after it connects, for the whole run (the "
                         "RV-resume chaos shape)")
    ap.add_argument("--max-relist-resyncs", type=int, default=None,
                    help="with --wire: fail if more than this many watch "
                         "reconnects fell back to a full LIST+diff resync "
                         "(0 = every reconnect resumed by resourceVersion)")
    ap.add_argument("--min-conn-reuse", type=float, default=None,
                    help="with --wire: fail if apiserver requests per "
                         "opened TCP connection drop below this (keep-"
                         "alive pooling regression guard)")
    ap.add_argument("--settle-s", type=float, default=0.0,
                    help="with --wire: keep the run alive this long after "
                         "convergence (idle-fleet watch chaos window)")
    ap.add_argument("--pool-warm", type=int, default=0,
                    help="with --wire: pre-warm a SlicePool with this "
                         "many slices before the fan-out so notebooks "
                         "BIND instead of cold-rolling; >= --count also "
                         "fails the run on any bind miss")
    ap.add_argument("--boot-delay-ms", type=float, default=0.0,
                    help="with --wire: simulated per-pod provisioning "
                         "cost (node spin-up + image pull) — what a warm "
                         "bind skips")
    args = ap.parse_args()
    if args.emit_yaml:
        try:
            for i in range(args.count):
                sys.stdout.write(
                    notebook_yaml(i, args.namespace, args.accelerator))
        except BrokenPipeError:
            pass  # downstream consumer (head, kubectl) closed the pipe
        return 0
    if args.wire:
        return run_wire(args.count, args.namespace, args.accelerator,
                        args.timeout,
                        max_requests_per_nb=args.max_requests_per_nb,
                        workers=args.workers,
                        apiserver_latency_ms=args.apiserver_latency_ms,
                        fault_rate=args.fault_rate,
                        fault_plan=args.fault_plan,
                        fault_seed=args.fault_seed,
                        list_page_size=args.list_page_size,
                        max_full_scans=args.max_full_scans,
                        preempt_rate=args.preempt_rate,
                        watch_kill_after_s=args.watch_kill_after_s,
                        max_relist_resyncs=args.max_relist_resyncs,
                        min_conn_reuse=args.min_conn_reuse,
                        settle_s=args.settle_s,
                        pool_warm=args.pool_warm,
                        boot_delay_ms=args.boot_delay_ms)
    return run_inprocess(args.count, args.namespace, args.accelerator,
                         args.timeout, server=args.server,
                         workers=args.workers)


if __name__ == "__main__":
    raise SystemExit(main())
