#!/usr/bin/env python3
"""Notebook fan-out load test.

Reference: notebook-controller/loadtest/start_notebooks.py:1-99 templates N
Notebook CRs (+ PVC each) and applies them with kubectl, as a manual
scalability probe. Two modes here:

- default (self-contained): drive the in-process control plane — apiserver,
  webhooks, both reconcilers, StatefulSet simulator — with N TPU notebooks
  and report creation→SliceReady latency percentiles and reconcile
  throughput. This is the control-plane scalability measurement the
  reference's script only eyeballs via kubectl.
- ``--emit-yaml``: print N templated Notebook CRs (with PVCs, like the
  reference's jupyter_test.yaml shape) for kubectl-apply against a real
  cluster.

Usage:
    python loadtest/start_notebooks.py --count 200
    python loadtest/start_notebooks.py --count 10 --emit-yaml | kubectl apply -f -
"""

from __future__ import annotations

import argparse
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def notebook_yaml(i: int, namespace: str, accelerator: str) -> str:
    return f"""---
apiVersion: v1
kind: PersistentVolumeClaim
metadata:
  name: loadtest-nb-{i}-pvc
  namespace: {namespace}
spec:
  accessModes: [ReadWriteOnce]
  resources:
    requests:
      storage: 10Gi
---
apiVersion: kubeflow.org/v1
kind: Notebook
metadata:
  name: loadtest-nb-{i}
  namespace: {namespace}
  annotations:
    tpu.kubeflow.org/accelerator: "{accelerator}"
spec:
  template:
    spec:
      containers:
      - name: loadtest-nb-{i}
        image: jupyter-minimal:latest
        volumeMounts:
        - name: workspace
          mountPath: /home/jovyan
      volumes:
      - name: workspace
        persistentVolumeClaim:
          claimName: loadtest-nb-{i}-pvc
"""


def run_inprocess(count: int, namespace: str, accelerator: str,
                  timeout: float, server: str | None = None) -> int:
    """Default: drive the in-process control plane. With ``server``: the
    same fan-out over REAL HTTP against a running apiserver (start one with
    ``python -m kubeflow_tpu.main --serve-apiserver PORT --simulate-kubelet``)
    — transport latency included in every number."""
    from kubeflow_tpu.api import types as api
    from kubeflow_tpu.utils import names

    mgr = None
    if server:
        from kubeflow_tpu.cluster.http_client import HttpApiClient
        store = HttpApiClient(server)
    else:
        from kubeflow_tpu.cluster.kubelet import StatefulSetSimulator
        from kubeflow_tpu.cluster.store import ClusterStore
        from kubeflow_tpu.controllers import setup_controllers

        store = ClusterStore()
        mgr = setup_controllers(store)
        StatefulSetSimulator(store, boot_delay_s=0.0).setup(mgr)
        mgr.start()
    created: dict[str, float] = {}
    ready: dict[str, float] = {}
    t0 = time.monotonic()
    for i in range(count):
        name = f"loadtest-nb-{i}"
        store.create(api.new_notebook(
            name, namespace,
            annotations={names.TPU_ACCELERATOR_ANNOTATION: accelerator}))
        created[name] = time.monotonic()
    deadline = time.monotonic() + timeout
    while len(ready) < count and time.monotonic() < deadline:
        for name in list(created):
            if name in ready:
                continue
            nb = store.get_or_none(api.KIND, namespace, name)
            cond = api.get_condition(nb, api.CONDITION_SLICE_READY) \
                if nb else None
            if cond and cond["status"] == "True":
                ready[name] = time.monotonic() - created[name]
        time.sleep(0.01)
    total = time.monotonic() - t0
    if mgr is not None:
        mgr.stop()
    if len(ready) < count:
        print(f"FAIL: only {len(ready)}/{count} notebooks became SliceReady "
              f"within {timeout}s")
        return 1
    lat = sorted(ready.values())
    print(f"notebooks: {count}  wall: {total:.2f}s  "
          f"throughput: {count/total:.1f} nb/s")
    print(f"create→SliceReady  p50: {statistics.median(lat)*1000:.1f}ms  "
          f"p95: {lat[int(0.95*(len(lat)-1))]*1000:.1f}ms  "
          f"max: {lat[-1]*1000:.1f}ms")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--count", type=int, default=50)
    ap.add_argument("--namespace", default="loadtest")
    ap.add_argument("--accelerator", default="v5e-4")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--emit-yaml", action="store_true",
                    help="print CRs for kubectl instead of running in-process")
    ap.add_argument("--server", default=None,
                    help="drive a running apiserver over HTTP instead of "
                         "the in-process stack (URL)")
    args = ap.parse_args()
    if args.emit_yaml:
        try:
            for i in range(args.count):
                sys.stdout.write(
                    notebook_yaml(i, args.namespace, args.accelerator))
        except BrokenPipeError:
            pass  # downstream consumer (head, kubectl) closed the pipe
        return 0
    return run_inprocess(args.count, args.namespace, args.accelerator,
                         args.timeout, server=args.server)


if __name__ == "__main__":
    raise SystemExit(main())
