#!/usr/bin/env python3
"""Notebook fan-out load test.

Reference: notebook-controller/loadtest/start_notebooks.py:1-99 templates N
Notebook CRs (+ PVC each) and applies them with kubectl, as a manual
scalability probe. Two modes here:

- default (self-contained): drive the in-process control plane — apiserver,
  webhooks, both reconcilers, StatefulSet simulator — with N TPU notebooks
  and report creation→SliceReady latency percentiles and reconcile
  throughput. This is the control-plane scalability measurement the
  reference's script only eyeballs via kubectl.
- ``--emit-yaml``: print N templated Notebook CRs (with PVCs, like the
  reference's jupyter_test.yaml shape) for kubectl-apply against a real
  cluster.

Usage:
    python loadtest/start_notebooks.py --count 200
    python loadtest/start_notebooks.py --count 10 --emit-yaml | kubectl apply -f -
"""

from __future__ import annotations

import argparse
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def notebook_yaml(i: int, namespace: str, accelerator: str) -> str:
    return f"""---
apiVersion: v1
kind: PersistentVolumeClaim
metadata:
  name: loadtest-nb-{i}-pvc
  namespace: {namespace}
spec:
  accessModes: [ReadWriteOnce]
  resources:
    requests:
      storage: 10Gi
---
apiVersion: kubeflow.org/v1
kind: Notebook
metadata:
  name: loadtest-nb-{i}
  namespace: {namespace}
  annotations:
    tpu.kubeflow.org/accelerator: "{accelerator}"
spec:
  template:
    spec:
      containers:
      - name: loadtest-nb-{i}
        image: jupyter-minimal:latest
        volumeMounts:
        - name: workspace
          mountPath: /home/jovyan
      volumes:
      - name: workspace
        persistentVolumeClaim:
          claimName: loadtest-nb-{i}-pvc
"""


def run_inprocess(count: int, namespace: str, accelerator: str,
                  timeout: float, server: str | None = None,
                  workers: int = 4) -> int:
    """Default: drive the in-process control plane. With ``server``: the
    same fan-out over REAL HTTP against a running apiserver (start one with
    ``python -m kubeflow_tpu.main --serve-apiserver PORT --simulate-kubelet``)
    — transport latency included in every number."""
    from kubeflow_tpu.api import types as api
    from kubeflow_tpu.utils import names

    mgr = None
    if server:
        from kubeflow_tpu.cluster.http_client import HttpApiClient
        store = HttpApiClient(server)
    else:
        from kubeflow_tpu.cluster.kubelet import StatefulSetSimulator
        from kubeflow_tpu.cluster.store import ClusterStore
        from kubeflow_tpu.controllers import setup_controllers

        store = ClusterStore()
        mgr = setup_controllers(store, max_concurrent_reconciles=workers)
        # indexed reads for the simulator too (shares the manager cache)
        StatefulSetSimulator(mgr.read_cache or store,
                             boot_delay_s=0.0).setup(mgr)
        mgr.start()
    created: dict[str, float] = {}
    ready: dict[str, float] = {}
    t0 = time.monotonic()
    for i in range(count):
        name = f"loadtest-nb-{i}"
        store.create(api.new_notebook(
            name, namespace,
            annotations={names.TPU_ACCELERATOR_ANNOTATION: accelerator}))
        created[name] = time.monotonic()
    deadline = time.monotonic() + timeout
    while len(ready) < count and time.monotonic() < deadline:
        for name in list(created):
            if name in ready:
                continue
            nb = store.get_or_none(api.KIND, namespace, name)
            cond = api.get_condition(nb, api.CONDITION_SLICE_READY) \
                if nb else None
            if cond and cond["status"] == "True":
                ready[name] = time.monotonic() - created[name]
        time.sleep(0.01)
    total = time.monotonic() - t0
    if mgr is not None:
        mgr.stop()
    if len(ready) < count:
        print(f"FAIL: only {len(ready)}/{count} notebooks became SliceReady "
              f"within {timeout}s")
        return 1
    print(f"notebooks: {count}  workers: {workers}  wall: {total:.2f}s  "
          f"throughput: {count/total:.1f} nb/s")
    _print_latencies(sorted(ready.values()))
    return 0


def run_mixed(namespace: str, accelerator: str, timeout: float,
              capacity: int = 8, training_slices: int = 4,
              serving_gangs: int = 2, waves: int = 3, wave_size: int = 3,
              dwell_s: float = 0.5, min_utilization: float = 0.5,
              # a quiet box measures ~0.90; the agent's step counter is
              # poll-thread-driven while the per-resize blip cost is
              # fixed, so a loaded CI box reads lower through no fault
              # of the scheduler — the floor keeps headroom for that
              min_mfu: float = 0.75, workers: int = 4,
              stats_out: dict | None = None) -> int:
    """Mixed-trace fleet-scheduler phase: a background elastic training
    run holds most of the fleet, a serving burst takes the remainder,
    and interactive gang storms arrive in waves sized so each wave can
    only fit by preempting the training run through the elastic shrink
    handshake. The full admission stack runs live — scheduler, repair
    controller, core reconciler, kubelet sim, a SimulatedElasticAgent
    acking the drains — and the run asserts the scheduler's fairness
    contract end to end:

    - NO TIER STARVES: every serving and interactive gang admits within
      its wave deadline, and the training run is back at its requested
      slice count (steps monotone, loss continuous, no hold left) once
      the storm subsides — preemption is a round-trip migration.
    - UTILIZATION FLOOR: time-averaged fleet usage, derived from the
      same annotations the scheduler admits against, stays at or above
      ``min_utilization`` for the storm's duration — admission control
      must pack the fleet, not park it.
    - NEVER OVERSUBSCRIBED: no usage sample exceeds capacity (the
      grow-back entitlement accounting under churn).
    - vacuous-pass guards: at least one preemption cascade actually ran
      (else the trace is undersized for the capacity), every scheduled
      hold was released, and the sampler took a real number of samples.
    """
    import threading

    from kubeflow_tpu.api import types as api
    from kubeflow_tpu.api.tpuquota import new_tpu_quota
    from kubeflow_tpu.cluster.kubelet import StatefulSetSimulator
    from kubeflow_tpu.cluster.store import ClusterStore
    from kubeflow_tpu.controllers import setup_controllers
    from kubeflow_tpu.controllers.scheduler import (SCHED_ADMITTED,
                                                    notebook_usage,
                                                    sched_state)
    from kubeflow_tpu.runtime.elastic import SimulatedElasticAgent
    from kubeflow_tpu.utils import names
    from kubeflow_tpu.utils.config import ControllerConfig
    from kubeflow_tpu.utils.metrics import MetricsRegistry

    train_ns = f"{namespace}-training"
    serve_ns = f"{namespace}-serving"
    inter_ns = f"{namespace}-interactive"
    cfg = ControllerConfig(
        sched_default_capacity=capacity, sched_poll_s=0.02,
        slice_repair_poll_s=0.02, slice_repair_backoff_base_s=0.01,
        slice_repair_backoff_max_s=0.05)
    metrics = MetricsRegistry()
    store = ClusterStore()
    mgr = setup_controllers(store, config=cfg, metrics=metrics,
                            max_concurrent_reconciles=workers)
    StatefulSetSimulator(mgr.read_cache or store,
                         boot_delay_s=0.0).setup(mgr)
    mgr.start()
    agent = None
    sampler_stop = threading.Event()
    samples: list[float] = []

    def _sample() -> None:
        while not sampler_stop.is_set():
            usage = sum(notebook_usage(nb) for nb in store.list(api.KIND))
            samples.append(usage / capacity)
            time.sleep(0.02)

    sampler = threading.Thread(target=_sample, daemon=True,
                               name="mixed-utilization-sampler")

    def _wait(predicate, deadline: float) -> bool:
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.01)
        return bool(predicate())

    def _spawn_gangs(tier: str, ns: str, count: int,
                     prefix: str) -> list[str]:
        out = []
        for i in range(count):
            nb_name = f"{prefix}-{i}"
            store.create(api.new_notebook(nb_name, ns, annotations={
                names.TPU_ACCELERATOR_ANNOTATION: accelerator,
                names.SCHED_GANG_ANNOTATION: "1",
                names.SCHED_TIER_ANNOTATION: tier,
            }))
            out.append(nb_name)
        return out

    def _admitted(ns: str, nbs: list[str]) -> bool:
        for nb_name in nbs:
            obj = store.get_or_none(api.KIND, ns, nb_name)
            if obj is None or sched_state(obj) != SCHED_ADMITTED:
                return False
        return True

    def _withdraw(ns: str, nbs: list[str]) -> None:
        for nb_name in nbs:
            store.patch(api.KIND, ns, nb_name, {
                "metadata": {"annotations": {
                    names.SCHED_GANG_ANNOTATION: None,
                    names.SCHED_TIER_ANNOTATION: None,
                }}})

    try:
        # per-tenant quotas sized to the trace: the admission path reads
        # them every pass; a withdrawn wave's not-yet-released
        # reservation makes the next wave's quota check bind briefly,
        # which is the transient-denial path being exercised
        for qname, tenant, cap in (
                ("mixed-training", train_ns, training_slices),
                ("mixed-serving", serve_ns, serving_gangs),
                ("mixed-interactive", inter_ns, wave_size)):
            store.create(new_tpu_quota(qname, tenant, cap))
        # background training: an elastic run holding most of the fleet
        store.create(api.new_notebook("bg-train", train_ns, annotations={
            names.TPU_ACCELERATOR_ANNOTATION: accelerator,
            names.ELASTIC_ANNOTATION: "true",
            names.ELASTIC_SLICES_ANNOTATION: str(training_slices),
            names.ELASTIC_CURRENT_SLICES_ANNOTATION: str(training_slices),
        }))
        agent = SimulatedElasticAgent(store, train_ns, "bg-train",
                                      current_slices=training_slices
                                      ).start()
        deadline = time.monotonic() + timeout
        if not _wait(lambda: agent.steps >= 20, deadline):
            print("FAIL: training agent banked no steps — elastic "
                  "runtime never reached Stable")
            return 1
        t0 = time.monotonic()
        sampler.start()

        # serving burst: takes the capacity the training run leaves free
        serving = _spawn_gangs("serving", serve_ns, serving_gangs, "serve")
        t_serve = time.monotonic()
        if not _wait(lambda: _admitted(serve_ns, serving), deadline):
            print(f"FAIL: serving tier starved — {serving} not all "
                  f"admitted within {timeout}s")
            return 1
        serving_wait = time.monotonic() - t_serve

        # interactive storm: each wave wants one slice more than the
        # fleet has free, so the last gang in every wave rides a
        # preemption cascade; the wave dwells, then withdraws, which
        # sweeps the hold and re-opens the training run's grow-back
        wave_waits: list[float] = []
        for w in range(waves):
            wave = _spawn_gangs("interactive", inter_ns, wave_size,
                                f"storm-{w}")
            t_wave = time.monotonic()
            if not _wait(lambda: _admitted(inter_ns, wave), deadline):
                stuck = [nb_name for nb_name in wave
                         if sched_state(store.get(api.KIND, inter_ns,
                                                  nb_name))
                         != SCHED_ADMITTED]
                print(f"FAIL: interactive tier starved — wave {w} gangs "
                      f"{stuck} never admitted")
                return 1
            wave_waits.append(time.monotonic() - t_wave)
            time.sleep(dwell_s)
            _withdraw(inter_ns, wave)
        _withdraw(serve_ns, serving)
        storm_wall = time.monotonic() - t0
        sampler_stop.set()
        sampler.join(timeout=5)

        # storm over: the training run must be made whole — the
        # "training tier never starves" half of the fairness contract
        def _training_restored() -> bool:
            nb = store.get(api.KIND, train_ns, "bg-train")
            anns = nb.get("metadata", {}).get("annotations", {}) or {}
            return (agent.current == training_slices
                    and anns.get(names.ELASTIC_RESIZE_ANNOTATION) is None
                    and anns.get(names.SCHED_PREEMPTED_ANNOTATION) is None)

        if not _wait(_training_restored, deadline):
            print(f"FAIL: training tier starved — run at {agent.current}/"
                  f"{training_slices} slices after the storm withdrew")
            return 1

        preempts = metrics.counter("scheduler_preemptions_total", "")
        scheduled = preempts.sum_where({"outcome": "scheduled"})
        released = preempts.sum_where({"outcome": "released"})
        util_mean = sum(samples) / len(samples) if samples else 0.0
        util_min = min(samples) if samples else 0.0
        util_max = max(samples) if samples else 0.0
        mfu = agent.mfu()
        print(f"mixed trace: capacity {capacity}  training "
              f"{training_slices}-slice elastic run  {serving_gangs} "
              f"serving + {waves}x{wave_size} interactive gangs  "
              f"storm wall: {storm_wall:.2f}s")
        print(f"tiers: serving admitted in {serving_wait:.2f}s  "
              f"interactive waves "
              f"{['%.2fs' % t for t in wave_waits]}  "
              f"preemptions: {scheduled:.0f} scheduled / "
              f"{released:.0f} released")
        print(f"utilization: mean {util_mean:.0%} min {util_min:.0%} "
              f"max {util_max:.0%} over {len(samples)} samples  "
              f"training: {agent.resizes} resizes, {agent.steps} steps, "
              f"mfu {mfu:.2f}, {len(agent.violations)} violations")
        if stats_out is not None:
            stats_out.update({
                "storm_wall_s": storm_wall,
                "serving_wait_s": serving_wait,
                "wave_waits_s": wave_waits,
                "preemptions_scheduled": scheduled,
                "preemptions_released": released,
                "utilization_mean": util_mean,
                "utilization_max": util_max,
                "samples": len(samples),
                "resizes": agent.resizes,
                "mfu": mfu,
                "violations": list(agent.violations),
            })
        if scheduled < 1:
            print("FAIL: the storm never forced a preemption — the trace "
                  "is undersized for the capacity (vacuous pass)")
            return 1
        if released < scheduled:
            print(f"FAIL: {scheduled - released:.0f} preemption hold(s) "
                  f"never released — grow-back gate leaked")
            return 1
        if len(samples) < 20:
            print(f"FAIL: only {len(samples)} utilization samples — the "
                  f"floor check is vacuous")
            return 1
        if util_max > 1.0 + 1e-9:
            print(f"FAIL: fleet oversubscribed — usage peaked at "
                  f"{util_max:.0%} of capacity")
            return 1
        if util_mean < min_utilization:
            print(f"FAIL: mean fleet utilization {util_mean:.0%} below "
                  f"the {min_utilization:.0%} floor — admission control "
                  f"parked capacity the trace wanted")
            return 1
        if agent.violations:
            print(f"FAIL: training telemetry violated elasticity "
                  f"invariants: {agent.violations[:3]}")
            return 1
        if agent.resizes < 2:
            print(f"FAIL: training run logged {agent.resizes} resize(s) — "
                  f"the preemption never round-tripped shrink + grow-back")
            return 1
        if mfu < min_mfu:
            print(f"FAIL: training mfu {mfu:.2f} under churn below the "
                  f"{min_mfu:.2f} floor")
            return 1
        return 0
    finally:
        sampler_stop.set()
        if agent is not None:
            agent.stop()
        mgr.stop()


def _print_latencies(lat: list[float]) -> None:
    """The shared create→SliceReady percentile line (both modes)."""
    if not lat:
        return
    print(f"create→SliceReady  p50: {statistics.median(lat)*1000:.1f}ms  "
          f"p95: {lat[int(0.95*(len(lat)-1))]*1000:.1f}ms  "
          f"max: {lat[-1]*1000:.1f}ms")


def _analyze_lifecycle_traces(recorder, namespace: str,
                              nb_names: list[str]
                              ) -> tuple[list[tuple[str, str]], dict]:
    """Check every notebook's flight-recorder traces for one COMPLETE
    CR→Ready lifecycle trace and aggregate a phase decomposition.

    Complete means: at least one recorded trace containing (a) a
    notebook-controller ``reconcile`` root, (b) ``workqueue.enqueue`` and
    ``workqueue.wait`` spans parented on such a root, (c) at least one
    ``rest.*`` wire span whose ancestry reaches a root, and (d) no span
    whose parent_id fails to resolve inside the trace (parentage intact
    end to end). The phase sums (queue + wire children) must also fit
    inside the reconcile-root wall within 10% — timestamps that don't
    nest mean the span plumbing lies about causality.

    Returns ``(problems, phases)``: per-notebook failure reasons (empty =
    all complete) and the fleet-aggregate ``{wall, queue, apf, wire,
    reconcile}`` seconds."""
    problems: list[tuple[str, str]] = []
    agg = {"wall": 0.0, "queue": 0.0, "apf": 0.0, "wire": 0.0,
           "reconcile": 0.0}
    for nb in nb_names:
        reason = "no trace recorded"
        best: dict | None = None
        for t in recorder.trace_for(namespace, nb):
            spans = t["spans"]
            by_id = {s["span_id"]: s for s in spans}
            roots = [s for s in spans if s["name"] == "reconcile"
                     and "notebook" in str(
                         s["attributes"].get("controller", ""))]
            if not roots:
                reason = "no notebook reconcile root"
                continue
            dangling = [s for s in spans
                        if s["parent_id"] and s["parent_id"] not in by_id]
            if dangling:
                reason = (f"{dangling[0]['name']} has a parent outside "
                          f"the trace (broken stitch)")
                continue
            root_ids = {s["span_id"] for s in roots}

            def _under_root(span: dict) -> bool:
                cur, seen = span, set()
                while cur is not None and cur["span_id"] not in seen:
                    if cur["span_id"] in root_ids:
                        return True
                    seen.add(cur["span_id"])
                    cur = (by_id.get(cur["parent_id"])
                           if cur["parent_id"] else None)
                return False

            waits = [s for s in spans if s["name"] == "workqueue.wait"
                     and s["parent_id"] in root_ids]
            enqueues = [s for s in spans if s["name"] == "workqueue.enqueue"
                        and s["parent_id"] in root_ids]
            wires = [s for s in spans if s["name"].startswith("rest.")
                     and _under_root(s)]
            if not enqueues:
                reason = "no workqueue.enqueue span under a root"
                continue
            if not waits:
                reason = "no workqueue.wait span under a root"
                continue
            if not wires:
                reason = "no wire span under a reconcile root"
                continue
            wall = sum(s["duration_s"] for s in roots)
            queue = sum(s["duration_s"] for s in waits + enqueues)
            wire = sum(s["duration_s"] for s in wires)
            apf = sum(s["duration_s"] for s in spans
                      if s["name"].startswith("apf.") and _under_root(s))
            if queue + wire > wall * 1.10:
                reason = (f"phase sums escape the reconcile wall: "
                          f"queue {queue:.3f}s + wire {wire:.3f}s vs "
                          f"wall {wall:.3f}s")
                continue
            best = {"wall": wall, "queue": queue, "apf": apf,
                    "wire": wire,
                    "reconcile": max(wall - queue - wire, 0.0)}
            break
        if best is None:
            problems.append((nb, reason))
        else:
            for k in agg:
                agg[k] += best[k]
    return problems, agg


def run_wire(count: int, namespace: str, accelerator: str, timeout: float,
             max_requests_per_nb: float | None = None,
             workers: int = 4, apiserver_latency_ms: float = 0.0,
             fault_rate: float = 0.0, fault_plan: str | None = None,
             fault_seed: int | None = 7,
             list_page_size: int | None = None,
             max_full_scans: int | None = None,
             preempt_rate: float = 0.0,
             watch_kill_after_s: float = 0.0,
             max_relist_resyncs: int | None = None,
             min_conn_reuse: float | None = None,
             settle_s: float = 0.0,
             pool_warm: int = 0,
             boot_delay_ms: float = 0.0,
             tenant_storm: int = 0,
             trace: bool = False,
             stats_out: dict | None = None) -> int:
    """Controller wire-cost measurement: the full controller stack runs
    over a real HTTP apiserver while the load generator drives the store
    directly, so ``rest_client_requests_total`` counts ONLY controller
    traffic. Reports apiserver requests per notebook — the number the
    reference's informer-cache architecture keeps small, and the regression
    guard for full-LIST/GET-storm patterns on the hot paths (metrics
    scrape, Event predicate).

    ``fault_rate`` arms the apiserver with the standard mixed wire-fault
    plan (429-with-Retry-After / 503 / connection reset per verb +
    watch-stream kills, cluster/faults.FaultPlan.uniform) at that
    per-request rate; ``fault_plan`` loads a custom plan YAML instead.
    With faults on, the run keeps an audit tap and fails on any duplicate
    side-effect write (a retried create applying twice) in addition to
    the convergence bound — the chaos soak contract.

    ``list_page_size`` pages every controller LIST through
    ``limit``/``continue`` chunks of that size (exercises pagination on
    the wire); ``max_full_scans`` bounds ``cache_full_scans_total`` — 0
    asserts the reconcile hot path never walks a whole cache kind.

    ``preempt_rate`` preempts the node under worker 0 of that fraction of
    the fleet mid-fan-out (each target's node is killed the moment its
    slice first reaches SliceReady — the worst time). The run then also
    fails on: any StatefulSet ever OBSERVED at a partial replica count
    (slice atomicity — replicas must only ever be 0 or the full worker
    count), any preempted slice not repaired back to SliceReady with its
    health state cleared, and any slice quarantined by a single
    preemption.

    ``watch_kill_after_s`` arms a watch-kill-only FaultPlan: EVERY watch
    stream is killed that long after connecting, for the whole run — the
    RV-resume chaos shape. ``max_relist_resyncs`` bounds
    ``watch_resumes_total{mode="relist"}`` (0 = every reconnect resumed
    from the server watch cache, zero full re-LISTs);
    ``min_conn_reuse`` bounds requests-per-connection from below (the
    keep-alive pool's proof that connections don't scale with requests).
    ``settle_s`` keeps the run alive that long after convergence so
    reconnect chaos actually happens on an idle fleet too.

    ``pool_warm`` pre-creates a SlicePool with that warm-slice target and
    waits for it to warm BEFORE the fan-out, so every notebook takes the
    bind path (controllers/slicepool.py); with pool_warm >= count the run
    fails on any bind miss (a notebook that cold-rolled). ``boot_delay_ms``
    is the simulated per-pod provisioning cost (node spin-up + image pull)
    — the cost a warm bind exists to not pay. ``stats_out`` (a dict)
    receives wall/p50/p95/req-per-notebook for phase-vs-phase comparisons.

    ``tenant_storm`` spins that many misbehaving-tenant threads for the
    whole fan-out: each hammers unpaginated Pod LISTs through its own
    client with a NON-controller User-Agent, so the apiserver's priority
    & fairness layer classifies them into the global-default flow — the
    isolation the APF chaos check pins (controller latency within 2x of
    the quiet baseline while the storm runs).

    ``trace`` records every reconcile in an in-process FlightRecorder
    (SDK tracing provider for the run's duration, restored afterwards)
    and fails the run unless EVERY notebook has a complete CR→Ready
    lifecycle trace — enqueue → queue-wait → reconcile root → wire spans
    with intact parentage — plus a per-phase wall decomposition whose
    queue+wire children fit inside the reconcile roots (within 10%)."""
    import tempfile

    from kubeflow_tpu.api import types as api
    from kubeflow_tpu.cluster.apiserver import ApiServerProxy
    from kubeflow_tpu.cluster.experiments import audit_duplicate_creates
    from kubeflow_tpu.cluster.faults import FaultPlan
    from kubeflow_tpu.cluster.http_client import HttpApiClient
    from kubeflow_tpu.cluster.kubelet import StatefulSetSimulator
    from kubeflow_tpu.cluster.store import ClusterStore
    from kubeflow_tpu.controllers import Manager, setup_controllers
    from kubeflow_tpu.utils import names
    from kubeflow_tpu.utils.metrics import MetricsRegistry

    plan = None
    audit_needed = False
    if fault_plan:
        plan = FaultPlan.from_file(fault_plan)
        audit_needed = True
    elif fault_rate > 0:
        plan = FaultPlan.uniform(fault_rate, seed=fault_seed)
        audit_needed = True
    elif watch_kill_after_s > 0:
        # watch-kill-only chaos: streams die, mutations never do — no
        # duplicate-write ambiguity to audit
        from kubeflow_tpu.cluster.faults import FAULT_WATCH_KILL, FaultRule
        plan = FaultPlan([FaultRule(FAULT_WATCH_KILL, 1.0,
                                    after_s=watch_kill_after_s)],
                         seed=fault_seed)
    audit_path = None
    if audit_needed:
        audit_file = tempfile.NamedTemporaryFile(suffix=".ndjson",
                                                 delete=False)
        audit_file.close()
        audit_path = audit_file.name

    from kubeflow_tpu.api.slicepool import install_slicepool_crd

    store = ClusterStore()
    api.install_notebook_crd(store)
    install_slicepool_crd(store)
    cleanups = []
    recorder = None
    if trace:
        from kubeflow_tpu.utils import tracing
        # traces_per_key raised well past the default ring: the kubelet
        # simulator's STS reconciles bind fresh traces to the same
        # ns/name key and must not evict the notebook lifecycle trace
        recorder = tracing.FlightRecorder(traces_per_key=64)
        prev_provider = tracing.get_provider()
        tracing.set_provider(tracing.SDKProvider(recorder))
        # appended FIRST so the reversed-cleanup order restores the
        # provider LAST, after every manager stopped emitting spans
        cleanups.append(lambda: tracing.set_provider(prev_provider))
    try:
        # the simulator reads through its own indexed informer cache (the
        # real STS controller's shape): pod lookups hit the 'statefulset'
        # by-label index instead of scanning the store's whole object map
        # per reconcile — at 2000 notebooks that scan is ~10k objects twice
        # per reconcile and dominates the cluster-side wall
        from kubeflow_tpu.cluster.cache import CachingClient
        sim_cache = CachingClient(store, auto_informer=False,
                                  disable_for=())
        sim_mgr = Manager(sim_cache, read_cache=sim_cache)
        StatefulSetSimulator(sim_cache,
                             boot_delay_s=boot_delay_ms / 1000.0
                             ).setup(sim_mgr)
        sim_mgr.start()
        cleanups.append(sim_mgr.stop)
        proxy = ApiServerProxy(store,
                               latency_s=apiserver_latency_ms / 1000.0,
                               fault_plan=plan, audit_log=audit_path)
        proxy.start()
        cleanups.append(proxy.stop)
        client = HttpApiClient(proxy.url, list_page_size=list_page_size)
        cleanups.append(client.close)
        metrics = MetricsRegistry()
        # one exposition for the whole watch path: the proxy registers the
        # serve-side coalescing counter and passes the registry down to
        # the store (watch-cache evictions)
        proxy.attach_metrics(metrics)
        mgr = setup_controllers(client, metrics=metrics,
                                max_concurrent_reconciles=workers)
        mgr.start()
        cleanups.append(mgr.stop)
        requests = metrics.counter("rest_client_requests_total", "")
        # let the watch backfills settle so the baseline excludes boot cost
        time.sleep(0.3)
        if pool_warm > 0:
            # warm the pool BEFORE the fan-out (and before the request
            # baseline: warm-up is capacity provisioning, not per-notebook
            # bind cost — exactly the cost split the pool exists for)
            from kubeflow_tpu.api.slicepool import new_slice_pool
            from kubeflow_tpu.utils.k8s import get_annotation
            store.create(new_slice_pool("loadtest-pool", accelerator,
                                        pool_warm))
            warm_deadline = time.monotonic() + timeout

            def _warm_count() -> int:
                return sum(
                    1 for s in store.list("StatefulSet", "tpu-slice-pools")
                    if get_annotation(s, names.POOL_STATE_ANNOTATION)
                    == names.POOL_STATE_WARM)
            while time.monotonic() < warm_deadline:
                if _warm_count() >= pool_warm:
                    break
                time.sleep(0.05)
            else:
                print(f"FAIL: pool never reached {pool_warm} warm slices "
                      f"(have {_warm_count()})")
                return 1
        import math
        import threading

        baseline = requests.total()
        # misbehaving-tenant LIST storm (APF chaos shape): each thread
        # loops unpaginated Pod LISTs under a tenant User-Agent; its
        # traffic lands in the global-default priority level, so its
        # seats/queues — not the controllers' — absorb the overload.
        # Tenant clients carry no metrics registry: storm requests never
        # pollute the controller req/nb accounting.
        storm_stop = threading.Event() if tenant_storm > 0 else None
        storm_threads: list = []
        storm_stats = {"requests": 0, "rejected": 0}
        storm_lock = threading.Lock()
        if tenant_storm > 0:
            from kubeflow_tpu.cluster.errors import ApiError

            def _storm(idx: int) -> None:
                tenant = HttpApiClient(
                    proxy.url, user_agent=f"tenant-lister-{idx}")
                try:
                    while not storm_stop.is_set():
                        try:
                            tenant.list("Pod", namespace)
                            ok = True
                        except ApiError:
                            ok = False  # 429'd through the retry budget
                        except Exception:  # noqa: BLE001 — teardown races
                            break
                        with storm_lock:
                            storm_stats["requests"] += 1
                            if not ok:
                                storm_stats["rejected"] += 1
                finally:
                    tenant.close()

            storm_threads = [
                threading.Thread(target=_storm, args=(i,), daemon=True,
                                 name=f"tenant-storm-{i}")
                for i in range(tenant_storm)]
            for t in storm_threads:
                t.start()
        # per-notebook create→SliceReady latency, observed via a store
        # watch — a tight full-LIST poll at a 500-notebook fan-out costs
        # ~17 ms/scan of deep copies and perturbs the very system under
        # measurement (it pins a core against the controllers' GIL time)
        from kubeflow_tpu.cluster.kubelet import kill_node
        from kubeflow_tpu.tpu import topology
        ready_at: dict[str, float] = {}
        all_ready = threading.Event()
        # slice-atomicity observer: EVERY StatefulSet write the apiserver
        # fans out must show replicas at 0 or the full worker count —
        # a partial value here is a broken repair/scale path, no matter
        # how briefly it existed
        full_workers = topology.parse_short_name(accelerator).num_workers
        partial_observed: list[tuple[str, object]] = []

        def on_sts_event(ev):
            if ev.type == "DELETED":
                return
            replicas = (ev.obj.get("spec") or {}).get("replicas")
            if replicas not in (0, full_workers):
                partial_observed.append(
                    (ev.obj["metadata"]["name"], replicas))
        store.watch("StatefulSet", on_sts_event, namespace=namespace)

        # node-preemption injection: the first ceil(count*rate) notebooks
        # lose the node under worker 0 the moment their slice first turns
        # Ready — mid-fan-out, while the controllers are busiest
        preempt_targets = {f"loadtest-nb-{i}"
                           for i in range(math.ceil(count * preempt_rate))} \
            if preempt_rate > 0 else set()
        preempted: set[str] = set()

        def _preempt(name: str) -> None:
            for pod in store.list("Pod", namespace,
                                  {names.NOTEBOOK_NAME_LABEL: name}):
                if pod.get("metadata", {}).get("labels", {}).get(
                        "apps.kubernetes.io/pod-index") == "0":
                    node = (pod.get("spec") or {}).get("nodeName")
                    if node:
                        kill_node(store, node)
                        preempted.add(name)
                    return

        def on_event(ev):
            nb = ev.obj
            name = nb["metadata"]["name"]
            if name not in ready_at and \
                    (api.get_condition(nb, api.CONDITION_SLICE_READY)
                     or {}).get("status") == "True":
                ready_at[name] = time.monotonic()
                if name in preempt_targets and name not in preempted:
                    _preempt(name)
                if len(ready_at) >= count:
                    all_ready.set()
        store.watch(api.KIND, on_event, namespace=namespace)

        if count <= 0:
            print("notebooks: 0 — nothing to do")
            return 0
        t0 = time.monotonic()
        created_at = {}
        for i in range(count):
            name = f"loadtest-nb-{i}"
            created_at[name] = time.monotonic()
            store.create(api.new_notebook(
                name, namespace,
                annotations={names.TPU_ACCELERATOR_ANNOTATION: accelerator}))
        all_ready.wait(timeout)
        # bind-path request cost snapshot AT convergence: pool re-warming
        # continues in the background (replacement capacity, not
        # per-notebook cost) and must not pollute the comparison
        converged_requests = requests.total()
        if storm_stop is not None:
            # the storm runs through the WHOLE fan-out (the isolation
            # under test); stop it at convergence so teardown is clean
            storm_stop.set()
            for t in storm_threads:
                t.join(timeout=10)
        if settle_s > 0:
            # idle-fleet window: watch chaos keeps firing while nothing
            # changes — reconnects must resume off bookmarks, not relist
            time.sleep(settle_s)
        # preempted slices must come back: repaired slice-atomically to
        # SliceReady with the health state cleared and NO quarantine (a
        # single preemption is normal fleet weather, not a poison pill)
        stuck_repairs: list[str] = []
        quarantined: list[str] = []
        if preempted:
            deadline = t0 + timeout

            def _unrepaired() -> list[str]:
                out = []
                for name in sorted(preempted):
                    nb = store.get_or_none(api.KIND, namespace, name)
                    if nb is None:
                        out.append(name)
                        continue
                    anns = nb.get("metadata", {}).get("annotations", {}) or {}
                    cond = (api.get_condition(nb, api.CONDITION_SLICE_READY)
                            or {})
                    if cond.get("status") != "True" or \
                            anns.get(names.SLICE_HEALTH_ANNOTATION):
                        out.append(name)
                return out

            while time.monotonic() < deadline:
                stuck_repairs = _unrepaired()
                if not stuck_repairs:
                    break
                time.sleep(0.05)
            else:
                stuck_repairs = _unrepaired()
            for name in sorted(preempted):
                nb = store.get_or_none(api.KIND, namespace, name)
                anns = (nb or {}).get("metadata", {}).get("annotations",
                                                          {}) or {}
                if anns.get(names.QUARANTINE_ANNOTATION):
                    quarantined.append(name)
        store.unwatch(on_event)
        store.unwatch(on_sts_event)
        ready = len(ready_at)
        wall = time.monotonic() - t0
        # one metrics scrape, so the notebook_running LIST cost is included
        metrics.expose()
        per_nb = (requests.total() - baseline) / max(count, 1)
        latencies = sorted(ready_at[n] - created_at[n] for n in ready_at)
        if stats_out is not None:
            stats_out.update({
                "wall_s": wall,
                "p50_s": statistics.median(latencies) if latencies else None,
                "p95_s": (latencies[int(0.95 * (len(latencies) - 1))]
                          if latencies else None),
                "req_per_nb": (converged_requests - baseline)
                / max(count, 1),
                "storm": dict(storm_stats) if tenant_storm else None,
            })
        if tenant_storm:
            print(f"tenant storm: {tenant_storm} threads, "
                  f"{storm_stats['requests']} LISTs, "
                  f"{storm_stats['rejected']} rejected through the retry "
                  f"budget (APF)")
        if ready < count:
            stuck = [n for n in created_at if n not in ready_at]
            print(f"FAIL: only {ready}/{count} notebooks became SliceReady "
                  f"within {timeout}s (stuck: {stuck[:5]}"
                  f"{'...' if len(stuck) > 5 else ''})")
            return 1
        faults_note = ""
        if plan is not None:
            injected = plan.injected()
            faults_note = (f"  injected faults: {plan.injected_total()} "
                           f"({dict(sorted(injected.items()))})")
        if preempted:
            repairs = metrics.counter("slice_repairs_total", "").total()
            faults_note += (f"  preempted nodes: {len(preempted)}  "
                            f"slice repairs: {repairs:.0f}")
        full_scans = metrics.counter("cache_full_scans_total", "").total()
        index_lookups = metrics.counter("cache_index_lookups_total",
                                        "").total()
        read_s = metrics.histogram("reconcile_read_seconds", "")
        write_s = metrics.histogram("reconcile_write_seconds", "")
        print(f"notebooks: {count}  workers: {workers}  wall: {wall:.2f}s  "
              f"controller apiserver requests/notebook: {per_nb:.1f}"
              f"{faults_note}")
        print(f"cache: {index_lookups:.0f} index lookups, "
              f"{full_scans:.0f} full scans  "
              f"phase wall: read {read_s.total_sum():.2f}s / "
              f"write {write_s.total_sum():.2f}s over "
              f"{read_s.total_count():.0f} reconciles")
        resumes_metric = metrics.counter("watch_resumes_total", "")
        resumed = resumes_metric.sum_where({"mode": "resume"})
        relisted = resumes_metric.sum_where({"mode": "relist"})
        evictions = metrics.counter("watch_cache_evictions_total",
                                    "").total()
        coalesced = metrics.counter("watch_queue_coalesced_total",
                                    "").total()
        conns_metric = metrics.counter("rest_client_connections_opened_total",
                                       "")
        pooled_conns = conns_metric.sum_where({"type": "pooled"})
        stream_conns = conns_metric.sum_where({"type": "stream"})
        reqs_total = requests.total()
        # reuse = request-path requests per pooled connection. Watch
        # connect GETs each ride a dedicated stream connection (one
        # stream = one connection by design; chaos churns those
        # legitimately) — subtract them from the numerator or every
        # stream would inflate the pooled ratio by ~1 request with no
        # pooled connection in the denominator
        pooled_reqs = max(reqs_total - stream_conns, 0.0)
        reuse = pooled_reqs / pooled_conns if pooled_conns else 0.0
        print(f"watch: {resumed:.0f} RV-resumes, {relisted:.0f} relist "
              f"resyncs, {evictions:.0f} cache evictions, "
              f"{coalesced:.0f} coalesced frames  "
              f"transport: {pooled_conns:.0f} pooled + {stream_conns:.0f} "
              f"stream connections for {reqs_total:.0f} requests "
              f"(reuse {reuse:.1f}x)")
        _print_latencies(sorted(ready_at[n] - created_at[n]
                                for n in ready_at))
        if max_requests_per_nb is not None and per_nb > max_requests_per_nb:
            print(f"FAIL: {per_nb:.1f} requests/notebook exceeds bound "
                  f"{max_requests_per_nb}")
            return 1
        if max_full_scans is not None and full_scans > max_full_scans:
            print(f"FAIL: {full_scans:.0f} cache full scans exceed bound "
                  f"{max_full_scans} (an unindexed hot-path LIST crept in)")
            return 1
        if max_relist_resyncs is not None:
            if watch_kill_after_s > 0 and resumed == 0:
                # vacuous-pass guard: the kill plan must actually have
                # forced reconnects for the zero-relist bound to mean
                # anything
                print("FAIL: watch-kill chaos armed but no RV-resume ever "
                      "happened (streams never reconnected?)")
                return 1
            if relisted > max_relist_resyncs:
                print(f"FAIL: {relisted:.0f} relist resyncs exceed bound "
                      f"{max_relist_resyncs} (a reconnect fell off the "
                      f"resume path)")
                return 1
        if min_conn_reuse is not None and reuse < min_conn_reuse:
            print(f"FAIL: connection reuse {reuse:.1f}x below bound "
                  f"{min_conn_reuse}x ({pooled_conns:.0f} pooled "
                  f"connections for {pooled_reqs:.0f} pooled-path requests "
                  f"— keep-alive pooling regressed)")
            return 1
        if recorder is not None:
            trace_problems, phases = _analyze_lifecycle_traces(
                recorder, namespace, sorted(created_at))
            complete = count - len(trace_problems)
            print(f"trace: {complete}/{count} complete CR→Ready traces  "
                  f"phase wall: queue {phases['queue']:.2f}s  "
                  f"apf {phases['apf']:.2f}s (inside wire)  "
                  f"wire {phases['wire']:.2f}s  "
                  f"reconcile {phases['reconcile']:.2f}s  "
                  f"of {phases['wall']:.2f}s reconcile wall")
            if stats_out is not None:
                stats_out["trace"] = {"complete": complete,
                                      "phases": phases}
            if trace_problems:
                print(f"FAIL: {len(trace_problems)} notebook(s) without a "
                      f"complete lifecycle trace: "
                      f"{trace_problems[:5]}")
                return 1
        if pool_warm > 0:
            from kubeflow_tpu.utils.k8s import get_annotation
            bound, missed = [], []
            for name in created_at:
                nb = store.get_or_none(api.KIND, namespace, name)
                if nb is None:
                    continue
                if get_annotation(nb, names.BOUND_SLICE_ANNOTATION):
                    bound.append(name)
                elif get_annotation(nb, names.POOL_BIND_MISS_ANNOTATION):
                    missed.append(name)
            print(f"pool: {len(bound)}/{count} warm-bound, "
                  f"{len(missed)} bind misses")
            if pool_warm >= count and missed:
                print(f"FAIL: pool had capacity for the whole fleet but "
                      f"{len(missed)} notebook(s) missed the bind path: "
                      f"{missed[:5]}")
                return 1
        if partial_observed:
            sample = partial_observed[:5]
            print(f"FAIL: {len(partial_observed)} partial-slice replica "
                  f"states observed (must only ever be 0 or "
                  f"{full_workers}): {sample}")
            return 1
        if stuck_repairs:
            print(f"FAIL: {len(stuck_repairs)} preempted notebook(s) not "
                  f"repaired back to SliceReady: {stuck_repairs[:5]}")
            return 1
        if quarantined:
            print(f"FAIL: single preemption quarantined {quarantined[:5]} "
                  f"(poison pill must need repeated FAILED repairs)")
            return 1
        if preempt_rate > 0 and not preempted:
            # vacuous-pass guard: a broken pod→node binding (or a drifted
            # worker-0 lookup) must fail the run, not silently skip every
            # repair assertion below
            print("FAIL: --preempt-rate set but no node was ever preempted "
                  "(worker-0 pods had no node binding?)")
            return 1
        if preempted:
            repairs = metrics.counter("slice_repairs_total", "").total()
            if repairs < len(preempted):
                # recovery without enough slice rolls means some slice
                # self-healed pod-by-pod — Ready pods, broken JAX mesh
                print(f"FAIL: {len(preempted)} preemptions but only "
                      f"{repairs:.0f} slice-atomic repairs (a worker was "
                      f"replaced without re-forming the mesh)")
                return 1
        if audit_path is not None:
            duplicates = audit_duplicate_creates(audit_path)
            if duplicates:
                print("FAIL: duplicate side-effect writes under faults:")
                for dup in duplicates:
                    print(f"  {dup}")
                return 1
            print("audit: no duplicate side-effect writes")
        return 0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(f"loadtest: cleanup failed: {e}\n")
        if audit_path is not None:
            try:
                Path(audit_path).unlink()
            except OSError:
                pass


class _DuplicateTracker:
    """Cross-manager duplicate-ownership detector: records which manager
    reconciled each notebook key and when. A key reconciled by two
    managers while BOTH were alive is a duplicate-owner reconcile — the
    invariant the shard leases exist to prevent. A key moving to the
    survivor AFTER a kill is the failover working."""

    def __init__(self) -> None:
        import threading
        self._lock = threading.Lock()
        self.touches: dict[tuple[str, str], list[tuple[int, float]]] = {}
        self.kill_time: float | None = None
        self.killed_manager: int | None = None

    def observer(self, manager_idx: int, controller_filter: str = "notebook"):
        def observe(controller: str, req) -> None:
            if controller_filter not in controller:
                return
            with self._lock:
                self.touches.setdefault(
                    (req.namespace, req.name), []).append(
                        (manager_idx, time.monotonic()))
        return observe

    def mark_kill(self, manager_idx: int) -> None:
        self.kill_time = time.monotonic()
        self.killed_manager = manager_idx

    def violations(self) -> list[tuple]:
        """Keys reconciled by >1 manager during a both-alive window:
        pre-kill, every manager counts; post-kill, the SURVIVORS must
        still be disjoint among themselves (a key moving from the killed
        manager to one survivor is the failover working — two survivors
        sharing it is the split-brain this exists to catch). Slightly
        conservative at ≥3 managers: a capacity-driven survivor-to-
        survivor handoff after the kill (legal, lease-serialized) is
        indistinguishable from overlap here and would be flagged."""
        out = []
        with self._lock:
            for key, touches in self.touches.items():
                pre = {m for m, t in touches
                       if self.kill_time is None or t < self.kill_time}
                post = {m for m, t in touches
                        if self.kill_time is not None
                        and t >= self.kill_time
                        and m != self.killed_manager}
                if len(pre) > 1 or len(post) > 1:
                    out.append((key, sorted(pre | post)))
        return out

    def managers_for(self, key: tuple[str, str]) -> set[int]:
        with self._lock:
            return {m for m, _ in self.touches.get(key, [])}


def _wait_for_shard_ownership(stacks, managers: int, shards: int,
                              deadline_s: float) -> bool:
    """Block until every manager owns EXACTLY its steady-state share for
    the full membership (`assign_shards` over all identities) — not a
    transient (the first manager briefly owns everything until its
    peers' member leases land; fanning out during that window would
    make the ensuing rebalance hand keys over mid-run). Shared by the
    sharded wire run and the soak."""
    from kubeflow_tpu.controllers.sharding import assign_shards
    identities = [f"m{m}" for m in range(managers)]
    expected = assign_shards(shards, identities)
    want = [frozenset(s for s, owner in expected.items() if owner == ident)
            for ident in identities]

    def settled() -> bool:
        return all(stack[0].sharding.owned_shards() == want[m]
                   for m, stack in enumerate(stacks))
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline and not settled():
        time.sleep(0.05)
    return settled()


def run_sharded(count: int, namespace: str, accelerator: str,
                timeout: float, managers: int, shards: int,
                workers: int = 4, namespace_count: int = 8,
                apiserver_latency_ms: float = 0.0,
                list_page_size: int | None = None,
                kill_manager_at_frac: float | None = None,
                extra_after_kill: int = 0,
                lease_duration_s: float = 10.0,
                renew_period_s: float = 1.0,
                frontends: int = 1, wire_format: str = "json",
                kill_frontend_at_frac: float | None = None,
                stats_out: dict | None = None) -> int:
    """Sharded multi-manager fan-out over the real wire: N manager stacks
    (each its own HttpApiClient + read cache + worker pool + per-shard
    lease election) reconcile one apiserver, ownership split by namespace
    hash into ``shards`` shards. Notebooks spread round-robin over
    ``namespace_count`` namespaces so every shard carries load.

    Measured per manager: owned shards, notebooks reconciled, apiserver
    requests — the per-shard req/nb + wall breakdown table. The
    reconcile-observer hook proves ZERO duplicate-owner reconciles (no
    key reconciled by two managers while both were alive).

    ``kill_manager_at_frac`` crashes manager 0 (leases left DANGLING, the
    hard-kill shape) once that fraction of the fleet is Ready; the
    survivors must adopt its shards within the lease duration and
    ``extra_after_kill`` more notebooks created post-kill must still
    converge — no lost notebooks.

    ``frontends`` replicates the apiserver facade: N ApiServerProxy
    instances over ONE sharded store, every client holding the full
    endpoint list (new connections rotate; connect failures fail over).
    ``wire_format="binary"`` moves the manager fleet onto the compact
    codec; a JSON watch-integrity observer always rides along when
    ``frontends > 1``, so the run doubles as the mixed-fleet
    serialize-once check and its event record is diffed against the
    store's own resume ring — zero lost, zero duplicated watch events.
    ``kill_frontend_at_frac`` hard-stops frontend 0 once that fraction
    of the fleet is Ready: every stream must fail over and RESUME by
    resourceVersion (zero relists pinned via the observer's metrics)."""
    import threading

    from kubeflow_tpu.api import types as api
    from kubeflow_tpu.api.slicepool import install_slicepool_crd
    from kubeflow_tpu.cluster.apiserver import ApiServerProxy
    from kubeflow_tpu.cluster.cache import CachingClient
    from kubeflow_tpu.cluster.errors import GoneError
    from kubeflow_tpu.cluster.http_client import HttpApiClient
    from kubeflow_tpu.cluster.kubelet import StatefulSetSimulator
    from kubeflow_tpu.cluster.store import ClusterStore
    from kubeflow_tpu.controllers import Manager, setup_controllers
    from kubeflow_tpu.utils import names
    from kubeflow_tpu.utils.config import ControllerConfig
    from kubeflow_tpu.utils.metrics import MetricsRegistry

    store = ClusterStore()
    api.install_notebook_crd(store)
    install_slicepool_crd(store)
    cleanups = []
    try:
        sim_cache = CachingClient(store, auto_informer=False, disable_for=())
        sim_mgr = Manager(sim_cache, read_cache=sim_cache)
        StatefulSetSimulator(sim_cache).setup(sim_mgr)
        sim_mgr.start()
        cleanups.append(sim_mgr.stop)
        server_metrics = MetricsRegistry(include_notebook_metrics=False)
        # replicated frontends: every proxy serves the same store and
        # attaches the same registry (get-or-create counters — the
        # fan-out/lock series aggregate across the fleet)
        proxies = []
        for _f in range(frontends):
            proxy = ApiServerProxy(store,
                                   latency_s=apiserver_latency_ms / 1000.0)
            proxy.attach_metrics(server_metrics)
            proxy.start()
            cleanups.append(proxy.stop)
            proxies.append(proxy)
        endpoints = ",".join(p.url for p in proxies)

        tracker = _DuplicateTracker()
        stacks = []  # (mgr, registry, requests_counter)
        for m in range(managers):
            client = HttpApiClient(endpoints, list_page_size=list_page_size,
                                   user_agent=f"kubeflow-tpu-manager/m{m}",
                                   wire_format=wire_format)
            cleanups.append(client.close)
            cfg = ControllerConfig(
                shard_count=shards, shard_identity=f"m{m}",
                shard_lease_duration_s=lease_duration_s,
                shard_renew_period_s=renew_period_s)
            reg = MetricsRegistry()
            mgr = setup_controllers(client, config=cfg, metrics=reg,
                                    max_concurrent_reconciles=workers)
            mgr.reconcile_observer = tracker.observer(m)
            mgr.start()
            cleanups.append(mgr.stop)
            stacks.append((mgr, reg, reg.counter(
                "rest_client_requests_total", "")))

        # ownership must settle BEFORE the fan-out (boot cost, like the
        # watch-backfill settle in run_wire)
        if not _wait_for_shard_ownership(stacks, managers, shards,
                                         min(timeout, 30.0)):
            print("FAIL: shard ownership never settled "
                  f"({[sorted(s[0].sharding.owned_shards()) for s in stacks]})")
            return 1

        # mixed-fleet watch-integrity observer (replicated-frontend runs):
        # a JSON watcher over the SAME rings the (possibly binary) manager
        # fleet consumes. Registered before any notebook exists, so its
        # delivered (type, name, rv) record can be diffed exactly against
        # the store's resume ring after convergence — lost or duplicated
        # watch events are counted, not inferred from convergence.
        obs_events: list[tuple] = []
        obs_lock = threading.Lock()
        obs_metrics = None
        if frontends > 1:
            obs_metrics = MetricsRegistry()
            observer = HttpApiClient(endpoints, metrics=obs_metrics,
                                     user_agent="kftpu-watch-observer")
            cleanups.append(observer.close)

            def _observe(ev):
                md = ev.obj.get("metadata", {})
                with obs_lock:
                    obs_events.append((ev.type, md.get("name"),
                                       int(md.get("resourceVersion", 0))))
            observer.watch(api.KIND, _observe)

        namespaces = [f"{namespace}-{i}" for i in range(namespace_count)]
        ready_at: dict[str, float] = {}
        ready_cv = threading.Condition()

        def on_event(ev):
            nb = ev.obj
            name = nb["metadata"]["name"]
            if name not in ready_at and \
                    (api.get_condition(nb, api.CONDITION_SLICE_READY)
                     or {}).get("status") == "True":
                with ready_cv:
                    ready_at[name] = time.monotonic()
                    ready_cv.notify_all()
        store.watch(api.KIND, on_event)

        baseline = [stack[2].total() for stack in stacks]
        t0 = time.monotonic()
        created_at: dict[str, float] = {}

        def _create(i: int) -> None:
            name = f"loadtest-nb-{i}"
            created_at[name] = time.monotonic()
            store.create(api.new_notebook(
                name, namespaces[i % namespace_count],
                annotations={names.TPU_ACCELERATOR_ANNOTATION: accelerator}))

        for i in range(count):
            _create(i)

        def _wait_ready(target: int, deadline: float) -> bool:
            with ready_cv:
                while len(ready_at) < target:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    ready_cv.wait(remaining)
                return True

        killed = False
        total = count
        deadline = t0 + timeout
        if kill_manager_at_frac is not None and managers > 1:
            if not _wait_ready(max(1, int(count * kill_manager_at_frac)),
                               deadline):
                print(f"FAIL: only {len(ready_at)}/{count} ready before "
                      f"the kill point")
                return 1
            # CRASH manager 0: election stops with leases left dangling,
            # then the worker pool dies. Survivors adopt its shards only
            # after the leases go stale — the real failover bound.
            tracker.mark_kill(0)
            stacks[0][0].sharding.stop(release=False)
            stacks[0][0].stop()
            killed = True
            for i in range(count, count + extra_after_kill):
                _create(i)
            total = count + extra_after_kill
        fe_killed_requests = None
        if kill_frontend_at_frac is not None and frontends > 1:
            if not _wait_ready(max(1, int(count * kill_frontend_at_frac)),
                               deadline):
                print(f"FAIL: only {len(ready_at)}/{count} ready before "
                      f"the frontend-kill point")
                return 1
            # hard-stop frontend 0: its sockets die mid-stream. Every
            # client holds the full endpoint list, so watches reconnect
            # on a surviving frontend and resume by resourceVersion —
            # the observer's relist counter pins that no stream fell
            # back to a LIST (zero missable gap)
            fe_killed_requests = proxies[0].requests_served
            proxies[0].stop()
        converged = _wait_ready(total, deadline)
        wall = time.monotonic() - t0
        store.unwatch(on_event)
        for _, reg, _ in stacks:
            reg.expose()  # one scrape each, notebook_running LIST included

        if not converged:
            stuck = [n for n in created_at if n not in ready_at]
            note = " — notebooks LOST in the failover (the survivor " \
                "never adopted the killed manager's shards)" if killed \
                else ""
            print(f"FAIL: only {len(ready_at)}/{total} notebooks became "
                  f"SliceReady within {timeout}s (stuck: {stuck[:5]}){note}")
            return 1

        duplicates = tracker.violations()
        # per-manager / per-shard breakdown
        per_manager = []
        reconciled_by = {}
        for key, touchers in ((k, tracker.managers_for(k))
                              for k in tracker.touches):
            for m in touchers:
                reconciled_by.setdefault(m, set()).add(key)
        lock_hist = server_metrics.histogram("store_list_lock_seconds", "")
        cache_lists = server_metrics.counter("apiserver_cache_lists_total",
                                             "").total()
        print(f"notebooks: {total}  managers: {managers}  shards: {shards}"
              f"  workers: {workers}/mgr  wall: {wall:.2f}s")
        print("| manager | shards owned | notebooks | requests | req/nb |")
        print("|---|---|---|---|---|")
        survivors_requests = 0.0
        for m, (mgr, reg, req_counter) in enumerate(stacks):
            owned = sorted(mgr.sharding.owned_shards()) \
                if not (killed and m == 0) else "(killed)"
            nbs = len(reconciled_by.get(m, ()))
            reqs = req_counter.total() - baseline[m]
            survivors_requests += reqs
            per_nb = reqs / max(nbs, 1)
            per_manager.append({"manager": m, "shards": owned,
                                "notebooks": nbs, "requests": reqs,
                                "req_per_nb": per_nb})
            print(f"| m{m} | {owned} | {nbs} | {reqs:.0f} | {per_nb:.1f} |")
        agg_req_nb = survivors_requests / max(total, 1)
        latencies = sorted(ready_at[n] - created_at[n] for n in ready_at)
        p50 = statistics.median(latencies) if latencies else 0.0
        p95 = latencies[int(0.95 * (len(latencies) - 1))] if latencies \
            else 0.0
        print(f"aggregate req/nb: {agg_req_nb:.1f}  p50: {p50*1000:.0f}ms  "
              f"p95: {p95*1000:.0f}ms  duplicate-owner reconciles: "
              f"{len(duplicates)}")
        write_hist = server_metrics.histogram("store_write_lock_seconds", "")
        print(f"store: {cache_lists:.0f} cache-served LISTs, "
              f"{lock_hist.total_count():.0f} store-lock LISTs holding "
              f"{lock_hist.total_sum()*1000:.1f}ms total, "
              f"{write_hist.total_count():.0f} writes holding "
              f"{write_hist.total_sum()*1000:.1f}ms total")
        fe_requests = [p.requests_served for p in proxies]
        fan_bytes = server_metrics.counter("watch_fanout_bytes_total", "")
        fan_frames = server_metrics.counter("watch_frames_sent_total", "")
        fanout = {enc: {"bytes": fan_bytes.sum_where({"encoding": enc}),
                        "frames": fan_frames.sum_where({"encoding": enc})}
                  for enc in ("binary", "json")}
        if frontends > 1:
            print("| frontend | requests |")
            print("|---|---|")
            for f, reqs in enumerate(fe_requests):
                tag = " (killed)" if fe_killed_requests is not None \
                    and f == 0 else ""
                print(f"| fe{f}{tag} | {reqs} |")
            for enc in ("binary", "json"):
                if fanout[enc]["frames"]:
                    print(f"watch fan-out [{enc}]: "
                          f"{fanout[enc]['bytes']:.0f} B over "
                          f"{fanout[enc]['frames']:.0f} frames = "
                          f"{fanout[enc]['bytes'] / fanout[enc]['frames']:.0f}"
                          f" B/event")
        watch_lost = watch_dup = None
        observer_relists = None
        if obs_metrics is not None:
            # quiesce, then snapshot the ring and wait for the observer
            # to catch up to it — the diff below is exact, not racy
            time.sleep(0.5)

            def _ring_sink(frame):
                return frame  # relay registered only to read the replay

            ring = None
            try:
                replay, _ = store.watch_frames(api.KIND, _ring_sink,
                                               since_rv=0)
                ring = {(f.type, f.obj["metadata"]["name"], f.rv)
                        for f in replay}
            except GoneError:
                print("watch-integrity: ring evicted at this scale — "
                      "per-name monotonicity check only")
            finally:
                store.unwatch(_ring_sink)
            settle = time.monotonic() + 10.0
            while ring is not None and time.monotonic() < settle:
                with obs_lock:
                    if ring <= set(obs_events):
                        break
                time.sleep(0.05)
            with obs_lock:
                events = list(obs_events)
            last_rv: dict[str, int] = {}
            watch_dup = 0
            for _t, nb_name, rv in events:
                if rv <= last_rv.get(nb_name, 0):
                    watch_dup += 1  # duplicate or reordered delivery
                else:
                    last_rv[nb_name] = rv
            if ring is not None:
                got = set(events)
                max_ring_rv = max((rv for _, _, rv in ring), default=0)
                watch_lost = len(ring - got)
                watch_dup += len({e for e in got - ring
                                  if e[2] <= max_ring_rv})
            observer_relists = obs_metrics.counter(
                "watch_resumes_total", "").sum_where({"mode": "relist"})
            print(f"watch-integrity: {len(events)} events observed, "
                  f"lost={watch_lost} dup={watch_dup} "
                  f"relists={observer_relists:.0f}")
            if watch_lost:
                print(f"FAIL: {watch_lost} watch events LOST across the "
                      f"replicated frontends (ring has them, the observer "
                      f"never saw them)")
                return 1
            if watch_dup:
                print(f"FAIL: {watch_dup} duplicated/reordered watch "
                      f"events delivered to the observer")
                return 1
            if observer_relists:
                print(f"FAIL: {observer_relists:.0f} observer reconnects "
                      f"fell back to a full relist — the resume cursor "
                      f"did not survive the frontend fleet")
                return 1
        if stats_out is not None:
            stats_out.update({
                "wall_s": wall, "req_per_nb": agg_req_nb, "p50_s": p50,
                "p95_s": p95, "duplicates": duplicates,
                "per_manager": per_manager,
                "store_lock_lists": lock_hist.total_count(),
                "store_lock_seconds": lock_hist.total_sum(),
                "store_lock_writes": write_hist.total_count(),
                "store_write_seconds": write_hist.total_sum(),
                "cache_lists": cache_lists,
                "frontend_requests": fe_requests,
                "killed_frontend_requests": fe_killed_requests,
                "fanout": fanout,
                "watch_events": len(obs_events),
                "watch_lost": watch_lost, "watch_dup": watch_dup,
                "observer_relists": observer_relists,
            })
        if duplicates:
            print(f"FAIL: {len(duplicates)} keys reconciled by multiple "
                  f"managers while both were alive: {duplicates[:5]}")
            return 1
        return 0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(f"loadtest: cleanup failed: {e}\n")


def run_soak(count: int, accelerator: str, timeout: float,
             managers: int, shards: int, workers: int = 4,
             namespace_count: int = 64, boot_delay_ms: float = 100.0,
             stats_out: dict | None = None, frontends: int = 0,
             wire_format: str = "binary",
             kill_frontend_at_frac: float | None = None) -> int:
    """100k-to-1M-notebook soak: the sharded CORE control plane. With
    ``frontends=0`` (the PR-15 shape) managers reconcile the store
    in-process — no HTTP wire. ``frontends=N`` is the 1M target profile:
    N replicated ApiServerProxy frontends over ONE sharded store, every
    manager an HttpApiClient on the compact binary wire holding the full
    endpoint list. The kubelet sim runs EVENT-DRIVEN boot ticks (one
    timer entry per pod, zero readiness polling) and no per-pod Node
    objects, so the soak's cost is reconcile logic, not simulator churn.

    Scope: core notebook reconciler only (extension/repair/pool off —
    their fan-outs multiply the object graph ~3x and are covered by the
    wire phases); single-worker slices. Asserted: full convergence, ZERO
    duplicate-owner reconciles, the store-lock LIST/write profile
    (store_list_lock_seconds / store_write_lock_seconds), and — on the
    wire profile — zero relist resyncs across the whole manager fleet
    (every watch reconnect, including the ``kill_frontend_at_frac``
    mid-soak frontend kill, resumed by resourceVersion: no missable
    gap, so no lost watch events)."""
    import threading

    from kubeflow_tpu.api import types as api
    from kubeflow_tpu.cluster.apiserver import ApiServerProxy
    from kubeflow_tpu.cluster.http_client import HttpApiClient
    from kubeflow_tpu.cluster.kubelet import StatefulSetSimulator
    from kubeflow_tpu.cluster.store import ClusterStore
    from kubeflow_tpu.controllers import Manager, setup_controllers
    from kubeflow_tpu.cluster.cache import CachingClient
    from kubeflow_tpu.utils import names
    from kubeflow_tpu.utils.config import ControllerConfig
    from kubeflow_tpu.utils.metrics import MetricsRegistry

    store = ClusterStore()
    server_metrics = MetricsRegistry(include_notebook_metrics=False)
    api.install_notebook_crd(store)
    cleanups = []
    try:
        sim_cache = CachingClient(store, auto_informer=False, disable_for=())
        sim_mgr = Manager(sim_cache, read_cache=sim_cache,
                          max_concurrent_reconciles=workers)
        StatefulSetSimulator(sim_cache, boot_delay_s=boot_delay_ms / 1000.0,
                             manage_nodes=False,
                             event_driven_boot=True).setup(sim_mgr)
        sim_mgr.start()
        cleanups.append(sim_mgr.stop)

        # replicated frontends (the 1M wire profile): all proxies share
        # one registry, so the fan-out/lock series aggregate fleet-wide
        proxies = []
        endpoints = None
        if frontends > 0:
            for _f in range(frontends):
                proxy = ApiServerProxy(store)
                proxy.attach_metrics(server_metrics)
                proxy.start()
                cleanups.append(proxy.stop)
                proxies.append(proxy)
            endpoints = ",".join(p.url for p in proxies)

        tracker = _DuplicateTracker()
        stacks = []
        for m in range(managers):
            # generous lease margin: a 100k soak pegs the CPU for tens of
            # minutes, and CPython's GIL convoy can starve the renew
            # thread for seconds at a stretch — a flapped lease is a
            # LEGAL serialized handoff, but it would churn ownership and
            # trip the strict duplicate-owner accounting this soak pins
            cfg = ControllerConfig(
                shard_count=shards, shard_identity=f"m{m}",
                shard_lease_duration_s=90.0, shard_renew_period_s=2.0,
                enable_slice_repair=False, enable_slice_pool=False)
            reg = MetricsRegistry()
            if endpoints is not None:
                backend = HttpApiClient(
                    endpoints, metrics=reg, wire_format=wire_format,
                    user_agent=f"kubeflow-tpu-manager/m{m}")
                cleanups.append(backend.close)
            else:
                backend = store
            # webhooks=False matches the wire loadtest's semantics (an
            # HTTP manager can't install in-process admission either) —
            # and the mutating webhook's odh stop-lock annotation would
            # park every notebook forever with the extension manager off
            mgr = setup_controllers(backend, config=cfg, metrics=reg,
                                    core=True, extension=False,
                                    webhooks=False,
                                    max_concurrent_reconciles=workers)
            mgr.reconcile_observer = tracker.observer(m)
            mgr.start()
            cleanups.append(mgr.stop)
            stacks.append((mgr, reg))
        # attach AFTER the managers: each setup_controllers passes its own
        # registry down to the shared store, and the LAST attach wins —
        # the soak's lock profile must land in server_metrics
        store.attach_metrics(server_metrics)
        if not _wait_for_shard_ownership(stacks, managers, shards, 30.0):
            print("FAIL: shard ownership never settled "
                  f"({[sorted(s[0].sharding.owned_shards()) for s in stacks]})")
            return 1

        ready = [0]
        ready_cv = threading.Condition()
        seen_ready: set[str] = set()

        def on_event(ev):
            nb = ev.obj
            name = nb["metadata"]["name"]
            if name not in seen_ready and \
                    (api.get_condition(nb, api.CONDITION_SLICE_READY)
                     or {}).get("status") == "True":
                with ready_cv:
                    if name in seen_ready:
                        return
                    seen_ready.add(name)
                    ready[0] += 1
                    ready_cv.notify_all()
        store.watch(api.KIND, on_event)

        kill_target = None
        if kill_frontend_at_frac is not None and frontends > 1:
            kill_target = max(1, int(count * kill_frontend_at_frac))
        fe_killed_requests = [None]

        def _maybe_kill_frontend(current: int) -> None:
            # mid-soak frontend kill: streams on fe0 die mid-event; every
            # client fails over to a surviving endpoint and resumes by rv
            if kill_target is not None and fe_killed_requests[0] is None \
                    and current >= kill_target:
                fe_killed_requests[0] = proxies[0].requests_served
                print(f"  mid-soak frontend kill: fe0 stopped at ready "
                      f"{current}/{count} "
                      f"({fe_killed_requests[0]} requests served)",
                      flush=True)
                proxies[0].stop()

        t0 = time.monotonic()
        report_every = max(count // 20, 1)
        for i in range(count):
            store.create(api.new_notebook(
                f"soak-nb-{i}", f"soak-{i % namespace_count}",
                annotations={names.TPU_ACCELERATOR_ANNOTATION: accelerator}))
            if (i + 1) % report_every == 0:
                elapsed = time.monotonic() - t0
                print(f"  created {i+1}/{count}, ready {ready[0]} "
                      f"({elapsed:.0f}s)", flush=True)
                _maybe_kill_frontend(ready[0])
        create_wall = time.monotonic() - t0
        deadline = t0 + timeout
        last_report = time.monotonic()
        while True:  # bounded: deadline-gated, breaks on convergence
            with ready_cv:
                if ready[0] < count and deadline > time.monotonic():
                    ready_cv.wait(min(deadline - time.monotonic(), 5.0))
                current = ready[0]
                done = current >= count or time.monotonic() >= deadline
            _maybe_kill_frontend(current)
            if time.monotonic() - last_report >= 30.0:
                last_report = time.monotonic()
                print(f"  draining: ready {current}/{count} "
                      f"({time.monotonic() - t0:.0f}s)", flush=True)
            if done:
                break
        wall = time.monotonic() - t0
        store.unwatch(on_event)
        converged = ready[0] >= count
        duplicates = tracker.violations()
        lock_hist = server_metrics.histogram("store_list_lock_seconds", "")
        write_hist = server_metrics.histogram("store_write_lock_seconds", "")
        shard_split = [sorted(s[0].sharding.owned_shards()) for s in stacks]
        # transitions beyond the initial settle mean ownership flapped
        # mid-run (a legal serialized handoff, but it churns resyncs)
        rebalances = sum(
            reg.counter("shard_rebalance_total", "").total()
            for _, reg in stacks)
        print(f"soak: {count} notebooks  managers: {managers}  shards: "
              f"{shards}  frontends: {frontends} ({wire_format} wire)  "
              f"wall: {wall:.1f}s (create phase "
              f"{create_wall:.1f}s)  ready: {ready[0]}/{count}")
        print(f"shard split: {shard_split}  ownership transitions: "
              f"{rebalances:.0f}")
        print(f"duplicate-owner reconciles: {len(duplicates)}  store-lock "
              f"LISTs: {lock_hist.total_count():.0f} holding "
              f"{lock_hist.total_sum()*1000:.1f}ms total  writes: "
              f"{write_hist.total_count():.0f} holding "
              f"{write_hist.total_sum()*1000:.1f}ms total")
        fe_requests = [p.requests_served for p in proxies]
        relists = resumes = 0.0
        if frontends > 0:
            print("| frontend | requests |")
            print("|---|---|")
            for f, reqs in enumerate(fe_requests):
                tag = " (killed)" if fe_killed_requests[0] is not None \
                    and f == 0 else ""
                print(f"| fe{f}{tag} | {reqs} |")
            for _, reg in stacks:
                resumes_counter = reg.counter("watch_resumes_total", "")
                relists += resumes_counter.sum_where({"mode": "relist"})
                resumes += resumes_counter.sum_where({"mode": "resume"})
            print(f"manager watch reconnects: {resumes:.0f} rv-resumes, "
                  f"{relists:.0f} relists")
        if stats_out is not None:
            stats_out.update({
                "wall_s": wall, "ready": ready[0],
                "duplicates": duplicates,
                "store_lock_lists": lock_hist.total_count(),
                "store_lock_seconds": lock_hist.total_sum(),
                "store_lock_writes": write_hist.total_count(),
                "store_write_seconds": write_hist.total_sum(),
                "frontend_requests": fe_requests,
                "killed_frontend_requests": fe_killed_requests[0],
                "relists": relists, "resumes": resumes,
            })
        if not converged:
            print(f"FAIL: only {ready[0]}/{count} notebooks became "
                  f"SliceReady within {timeout}s")
            return 1
        if duplicates:
            print(f"FAIL: {len(duplicates)} duplicate-owner reconciles: "
                  f"{duplicates[:5]}")
            return 1
        if frontends > 0 and relists:
            print(f"FAIL: {relists:.0f} manager watch reconnects fell back "
                  f"to a full relist — a resume cursor was lost across the "
                  f"frontend fleet (missable gap ⇒ potentially lost watch "
                  f"events)")
            return 1
        return 0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(f"loadtest: cleanup failed: {e}\n")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--count", type=int, default=50)
    ap.add_argument("--namespace", default="loadtest")
    ap.add_argument("--accelerator", default="v5e-4")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--emit-yaml", action="store_true",
                    help="print CRs for kubectl instead of running in-process")
    ap.add_argument("--server", default=None,
                    help="drive a running apiserver over HTTP instead of "
                         "the in-process stack (URL)")
    ap.add_argument("--wire", action="store_true",
                    help="run the controllers over a local HTTP apiserver "
                         "and report apiserver requests per notebook")
    ap.add_argument("--max-requests-per-nb", type=float, default=None,
                    help="with --wire: fail if controller apiserver "
                         "requests per notebook exceed this bound")
    ap.add_argument("--workers", type=int, default=4,
                    help="manager MaxConcurrentReconciles (dispatch "
                         "worker-pool size; 1 = single-thread baseline)")
    ap.add_argument("--apiserver-latency-ms", type=float, default=0.0,
                    help="with --wire: inject this request round-trip "
                         "latency at the apiserver (a localhost facade "
                         "has ~0 RTT; production apiservers have 1-10 ms "
                         "— the regime concurrent dispatch exists for)")
    ap.add_argument("--fault-rate", type=float, default=0.0,
                    help="with --wire: per-request probability of an "
                         "injected wire fault (429/503/reset/watch-kill "
                         "mix, cluster/faults.FaultPlan.uniform); the run "
                         "also fails on any duplicate side-effect write")
    ap.add_argument("--fault-plan", default=None, metavar="PATH",
                    help="with --wire: load a custom FaultPlan YAML "
                         "instead of the uniform mix")
    ap.add_argument("--fault-seed", type=int, default=7,
                    help="seed for the injected-fault RNG (replayable runs)")
    ap.add_argument("--list-page-size", type=int, default=None,
                    help="with --wire: page every controller LIST through "
                         "limit/continue chunks of this size (exercises "
                         "apiserver pagination on the wire; bounds resync "
                         "memory on big fleets)")
    ap.add_argument("--max-full-scans", type=int, default=None,
                    help="with --wire: fail if cache_full_scans_total "
                         "exceeds this (0 = assert the reconcile hot path "
                         "never walks a whole cache kind)")
    ap.add_argument("--preempt-rate", type=float, default=0.0,
                    help="with --wire: preempt the node under worker 0 of "
                         "this fraction of the fleet as each slice first "
                         "turns Ready; the run fails on any partially "
                         "scaled StatefulSet, unrepaired slice, or "
                         "quarantine from a single preemption")
    ap.add_argument("--watch-kill-after-s", type=float, default=0.0,
                    help="with --wire: kill EVERY watch stream this long "
                         "after it connects, for the whole run (the "
                         "RV-resume chaos shape)")
    ap.add_argument("--max-relist-resyncs", type=int, default=None,
                    help="with --wire: fail if more than this many watch "
                         "reconnects fell back to a full LIST+diff resync "
                         "(0 = every reconnect resumed by resourceVersion)")
    ap.add_argument("--min-conn-reuse", type=float, default=None,
                    help="with --wire: fail if apiserver requests per "
                         "opened TCP connection drop below this (keep-"
                         "alive pooling regression guard)")
    ap.add_argument("--settle-s", type=float, default=0.0,
                    help="with --wire: keep the run alive this long after "
                         "convergence (idle-fleet watch chaos window)")
    ap.add_argument("--pool-warm", type=int, default=0,
                    help="with --wire: pre-warm a SlicePool with this "
                         "many slices before the fan-out so notebooks "
                         "BIND instead of cold-rolling; >= --count also "
                         "fails the run on any bind miss")
    ap.add_argument("--boot-delay-ms", type=float, default=0.0,
                    help="with --wire: simulated per-pod provisioning "
                         "cost (node spin-up + image pull) — what a warm "
                         "bind skips")
    ap.add_argument("--tenant-storm", type=int, default=0, metavar="N",
                    help="with --wire: run N misbehaving-tenant threads "
                         "hammering unpaginated Pod LISTs under a tenant "
                         "User-Agent for the whole fan-out — the APF "
                         "isolation chaos shape")
    ap.add_argument("--trace", action="store_true",
                    help="with --wire: record every reconcile in a "
                         "flight recorder and fail unless each notebook "
                         "has a complete CR→Ready trace (enqueue → "
                         "queue-wait → reconcile → wire, intact "
                         "parentage); reports the queue/APF/wire/"
                         "reconcile phase breakdown")
    ap.add_argument("--managers", type=int, default=0, metavar="N",
                    help="sharded multi-manager mode: run N full manager "
                         "stacks (own client/cache/worker pool/per-shard "
                         "leases) against one apiserver over the wire; "
                         "requires --shards")
    ap.add_argument("--shards", type=int, default=0, metavar="M",
                    help="shard count for --managers/--soak (namespace-"
                         "hash reconcile ownership)")
    ap.add_argument("--namespace-count", type=int, default=8,
                    help="spread notebooks over this many namespaces "
                         "(sharded/soak modes; 1 namespace = 1 shard's "
                         "worth of load)")
    ap.add_argument("--kill-manager-at", type=float, default=None,
                    metavar="FRAC",
                    help="with --managers: crash manager 0 (leases left "
                         "dangling) once FRAC of the fleet is Ready; "
                         "survivors must adopt its shards and no "
                         "notebook may be lost")
    ap.add_argument("--mixed-trace", action="store_true",
                    help="fleet-scheduler mixed-trace phase: background "
                         "elastic training + serving burst + interactive "
                         "gang-storm waves arbitrated by the scheduler; "
                         "fails on tier starvation, a sub-floor fleet "
                         "utilization, oversubscription, or a missing "
                         "preemption cascade (see run_mixed)")
    ap.add_argument("--soak", action="store_true",
                    help="100k-to-1M-scale soak: sharded core control "
                         "plane with event-driven kubelet ticks (uses "
                         "--count/--managers/--shards/--namespace-count; "
                         "add --frontends N for the replicated-frontend "
                         "wire profile; see run_soak)")
    ap.add_argument("--frontends", type=int, default=0, metavar="N",
                    help="replicate the apiserver facade: N frontends "
                         "over one sharded store, every client holding "
                         "the full endpoint list (sharded runs default "
                         "to 1; the soak's wire profile needs >= 2)")
    ap.add_argument("--wire-format", choices=("json", "binary"),
                    default="binary",
                    help="manager-fleet wire encoding for --frontends "
                         "runs (json stays the default/debug path "
                         "elsewhere)")
    ap.add_argument("--kill-frontend-at", type=float, default=None,
                    metavar="FRAC",
                    help="hard-stop frontend 0 once FRAC of the fleet "
                         "is Ready: every stream must fail over and "
                         "resume by resourceVersion (needs "
                         "--frontends >= 2)")
    args = ap.parse_args()
    if args.emit_yaml:
        try:
            for i in range(args.count):
                sys.stdout.write(
                    notebook_yaml(i, args.namespace, args.accelerator))
        except BrokenPipeError:
            pass  # downstream consumer (head, kubectl) closed the pipe
        return 0
    if args.mixed_trace:
        return run_mixed(args.namespace, args.accelerator, args.timeout,
                         workers=args.workers)
    if args.soak:
        return run_soak(args.count, args.accelerator, args.timeout,
                        managers=max(args.managers, 1),
                        shards=args.shards or 8, workers=args.workers,
                        namespace_count=args.namespace_count,
                        boot_delay_ms=args.boot_delay_ms,
                        frontends=args.frontends,
                        wire_format=args.wire_format,
                        kill_frontend_at_frac=args.kill_frontend_at)
    if args.managers > 0:
        return run_sharded(args.count, args.namespace, args.accelerator,
                           args.timeout, managers=args.managers,
                           shards=args.shards or args.managers * 2,
                           workers=args.workers,
                           namespace_count=args.namespace_count,
                           apiserver_latency_ms=args.apiserver_latency_ms,
                           list_page_size=args.list_page_size,
                           kill_manager_at_frac=args.kill_manager_at,
                           extra_after_kill=(max(args.count // 10, 4)
                                             if args.kill_manager_at
                                             else 0),
                           frontends=max(args.frontends, 1),
                           wire_format=(args.wire_format
                                        if args.frontends else "json"),
                           kill_frontend_at_frac=args.kill_frontend_at)
    if args.wire:
        return run_wire(args.count, args.namespace, args.accelerator,
                        args.timeout,
                        max_requests_per_nb=args.max_requests_per_nb,
                        workers=args.workers,
                        apiserver_latency_ms=args.apiserver_latency_ms,
                        fault_rate=args.fault_rate,
                        fault_plan=args.fault_plan,
                        fault_seed=args.fault_seed,
                        list_page_size=args.list_page_size,
                        max_full_scans=args.max_full_scans,
                        preempt_rate=args.preempt_rate,
                        watch_kill_after_s=args.watch_kill_after_s,
                        max_relist_resyncs=args.max_relist_resyncs,
                        min_conn_reuse=args.min_conn_reuse,
                        settle_s=args.settle_s,
                        pool_warm=args.pool_warm,
                        boot_delay_ms=args.boot_delay_ms,
                        tenant_storm=args.tenant_storm,
                        trace=args.trace)
    return run_inprocess(args.count, args.namespace, args.accelerator,
                         args.timeout, server=args.server,
                         workers=args.workers)


if __name__ == "__main__":
    raise SystemExit(main())
